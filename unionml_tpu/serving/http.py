"""Minimal asyncio HTTP/1.1 server for model serving.

Replaces the reference's FastAPI/uvicorn dependency (unionml/fastapi.py) with a
self-contained server: request-line + header parsing, Content-Length bodies, JSON
responses, HTTP/1.1 keep-alive (persistent connections with an idle timeout — a
benchmark client reusing one connection pays the TCP/loopback handshake once, not
per request), and the overload posture the reference left to uvicorn/Flyte:
in-flight admission control (429 + Retry-After past the cap), per-request
deadlines (``X-Request-Deadline-Ms``, 503 on expiry, handler cancelled), and
SIGTERM graceful drain (readiness off, in-flight streams finish, then exit) —
see docs/serving.md "Serving under load". Deliberately small — the serving
surface is five routes — and dependency-free so the serving container stays
lean on TPU VMs.
"""

from __future__ import annotations

import asyncio
import contextvars
import json
import signal
import time
import urllib.parse
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from unionml_tpu._logging import logger
from unionml_tpu.defaults import SERVE_DRAIN_TIMEOUT_S, SERVE_MAX_DEADLINE_MS, SERVE_RETRY_AFTER_S
from unionml_tpu.observability.trace import (
    REQUEST_ID_HEADER,
    bind as _bind_request,
    new_request_id,
    sanitize_request_id,
    unbind as _unbind_request,
)
from unionml_tpu.serving.overload import (
    DeadlineExceeded,
    QueueFullError,
    TenantThrottled,
    remaining_s,
    request_deadline,
)
from unionml_tpu.serving.tenancy import (
    AUTHORIZATION_HEADER,
    PRIORITY_HEADER,
    TENANT_HEADER,
    active_registry,
    bind_tenant as _bind_tenant,
    parse_priority,
    priority_name,
    resolve_tenant,
    unbind_tenant as _unbind_tenant,
)

Handler = Callable[[bytes], Awaitable[Tuple[int, Any, str]]]

_STATUS_PHRASES = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

#: query parameters of the request currently being handled, bound by
#: ``_dispatch_full`` — handlers read them via :func:`current_query` instead of
#: a signature change on the Handler protocol (``/metrics?format=prometheus``,
#: ``/debug/requests?route=...``)
request_query: "contextvars.ContextVar[Dict[str, str]]" = contextvars.ContextVar(
    "request_query", default={}
)


def current_query() -> "Dict[str, str]":
    """The active request's parsed query-string parameters."""
    return request_query.get()

MAX_BODY_BYTES = 64 * 1024 * 1024
KEEPALIVE_IDLE_S = 75.0

#: the client's deadline header: milliseconds this request is still worth
#: serving. Clipped to ``max_deadline_ms``; absent -> ``default_deadline_ms``.
DEADLINE_HEADER = "x-request-deadline-ms"


class HTTPServer:
    """Route table + asyncio socket loop, with admission control and deadlines.

    Overload posture (all opt-in at this layer; :class:`ServingApp` turns them
    on with the ``defaults.py`` values): ``max_inflight`` bounds concurrently
    executing handlers — excess requests shed immediately with ``429`` +
    ``Retry-After`` instead of queueing; ``default_deadline_ms`` bounds every
    handler (a request past its deadline is cancelled and answered ``503``);
    ``begin_drain()``/``shutdown()`` implement graceful drain — readiness flips
    (non-exempt routes get ``503``), in-flight work finishes under
    ``drain_timeout_s``, then ``serve()`` returns. ``serve()`` installs a
    SIGTERM handler wired to ``shutdown()`` so rolling restarts on a TPU slice
    drain live decodes instead of dropping them.
    """

    def __init__(self) -> None:
        self._routes: Dict[Tuple[str, str], Handler] = {}
        #: prefix routes (``/debug/requests/<id>``): handler receives the path
        #: suffix as a second argument; exact routes always win
        self._prefix_routes: Dict[Tuple[str, str], Callable[[bytes, str], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        #: optional sink with a ``record(route, status, latency_s)`` method
        #: (:class:`unionml_tpu.serving.metrics.ServingMetrics`)
        self.metrics: Any = None
        #: optional :class:`~unionml_tpu.observability.trace.Tracer`: when set
        #: and enabled, every request gets a RequestTrace timeline registered
        #: in the app's flight recorder. Request IDS flow regardless — inbound
        #: ``X-Request-Id`` honored, generated otherwise, echoed on every
        #: response including errors and sheds.
        self.tracer: Any = None
        #: one structured line per completed request (request id attached via
        #: the contextvar, so JSON-format logs correlate with traces); off by
        #: default — the bare server stays silent on the request path
        self.access_log: bool = False
        # ---- overload knobs (None = unbounded, the bare-server default;
        # ServingApp applies the production defaults from defaults.py)
        self.max_inflight: Optional[int] = None
        self.default_deadline_ms: Optional[float] = None
        self.max_deadline_ms: Optional[float] = SERVE_MAX_DEADLINE_MS
        self.retry_after_s: float = SERVE_RETRY_AFTER_S
        self.drain_timeout_s: float = SERVE_DRAIN_TIMEOUT_S
        #: called once by ``shutdown()`` after in-flight work drains — the app
        #: hook that closes its batching engines
        self.on_drained: Optional[Callable[[], None]] = None
        # ---- overload state
        self.draining = False
        self._inflight = 0
        self._streams = 0
        #: routes that keep answering while draining (health must report
        #: ready=false, metrics must stay scrapable through the drain, and the
        #: flight recorder and fleet-health views are most useful exactly
        #: while a drain is stuck)
        self._drain_exempt = {
            ("GET", "/health"), ("GET", "/healthz"), ("GET", "/metrics"),
            ("GET", "/debug/requests"), ("GET", "/debug/fleet"),
        }
        self._stop_serving: Optional[asyncio.Event] = None

    @property
    def inflight(self) -> int:
        """Concurrently executing handlers + live streaming responses."""
        return self._inflight + self._streams

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._routes[(method.upper(), path)] = handler

    def route_prefix(
        self, method: str, prefix: str, handler: "Callable[[bytes, str], Awaitable[Any]]"
    ) -> None:
        """Register a prefix route: requests whose path extends ``prefix`` call
        ``handler(body, suffix)``. Exact routes win over prefixes, and the
        metrics label is the prefix + ``*`` (bounded cardinality — arbitrary
        suffixes must not mint metric routes)."""
        self._prefix_routes[(method.upper(), prefix)] = handler

    def _resolve(self, method: str, path: str) -> "Tuple[Optional[Handler], Optional[str]]":
        """``(handler, metrics_route)`` for a request path: exact match first,
        then the longest matching prefix route (its suffix is bound into the
        returned handler)."""
        handler = self._routes.get((method, path))
        if handler is not None:
            return handler, f"{method} {path}"
        best: Optional[Tuple[str, Callable[[bytes, str], Awaitable[Any]]]] = None
        for (pmethod, prefix), phandler in self._prefix_routes.items():
            if pmethod == method and path.startswith(prefix) and len(path) > len(prefix):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, phandler)
        if best is None:
            return None, None
        prefix, phandler = best
        suffix = path[len(prefix):]

        async def bound(body: bytes) -> Any:
            return await phandler(body, suffix)

        return bound, f"{method} {prefix}*"

    async def _read_request(
        self, reader: asyncio.StreamReader, request_line: Optional[bytes] = None
    ) -> Optional[Tuple[str, str, bytes, bool, bool, Dict[str, str]]]:
        if request_line is None:
            request_line = await reader.readline()
        if not request_line:
            return None
        try:
            method, target, version = request_line.decode("latin1").split(" ", 2)
        except ValueError:
            raise ValueError("malformed request line")
        # the query string rides along; _dispatch_full splits and parses it so
        # the in-process test client (`dispatch("GET", "/metrics?format=...")`)
        # behaves exactly like the wire
        path = target

        content_length = 0
        # HTTP/1.1 defaults to persistent connections; 1.0 must opt in
        http10 = "1.0" in version
        keep_alive = not http10
        wants_close = False
        headers: Dict[str, str] = {}
        while True:
            header_line = await reader.readline()
            if header_line in (b"\r\n", b"\n", b""):
                break
            name, _, value = header_line.decode("latin1").partition(":")
            name = name.strip().lower()
            headers[name] = value.strip()
            if name == "content-length":
                try:
                    content_length = int(value.strip())
                except ValueError:
                    raise ValueError("malformed Content-Length")
                if content_length < 0:
                    # readexactly(-n) would raise its own confusing ValueError;
                    # reject the protocol violation with a clean 400 instead
                    raise ValueError("negative Content-Length")
            elif name == "connection":
                # the value is a comma-separated token list ("close, TE"); an
                # explicit close wins over everything, including later headers
                tokens = {t.strip().lower() for t in value.split(",")}
                if "close" in tokens:
                    keep_alive = False
                    wants_close = True
                elif "keep-alive" in tokens and not wants_close:
                    keep_alive = True
        if content_length > MAX_BODY_BYTES:
            raise ValueError("request body too large")
        body = await reader.readexactly(content_length) if content_length else b""
        return method.upper(), path, body, keep_alive, http10, headers

    @staticmethod
    def _extra_header_lines(extra_headers: Optional[Dict[str, str]]) -> str:
        if not extra_headers:
            return ""
        return "".join(f"{name}: {value}\r\n" for name, value in extra_headers.items())

    @classmethod
    def _encode_stream_head(
        cls, status: int, content_type: str, *, keep_alive: bool, http10: bool,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> bytes:
        """Response head for a streaming body. HTTP/1.0 peers cannot parse chunked
        framing, so they get an unframed close-delimited body instead."""
        connection = "keep-alive" if (keep_alive and not http10) else "close"
        framing = "" if http10 else "Transfer-Encoding: chunked\r\n"
        return (
            f"HTTP/1.1 {status} {_STATUS_PHRASES.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"{framing}"
            f"{cls._extra_header_lines(extra_headers)}"
            f"Connection: {connection}\r\n\r\n"
        ).encode("latin1")

    @staticmethod
    async def _write_stream(
        writer: asyncio.StreamWriter, payload: Any, *, http10: bool,
        deadline: Optional[float] = None,
    ) -> None:
        """Emit an async-iterator payload, draining per chunk so each arrives as
        soon as it is produced: chunked transfer encoding for HTTP/1.1, raw bytes
        delimited by connection close for HTTP/1.0. A ``deadline`` (absolute
        monotonic, set only for explicit client deadlines) truncates the stream
        at the next chunk boundary — the caller's abort path then acloses the
        payload, which releases the producer (e.g. a continuous-batching slot)."""
        async for chunk in payload:
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded("stream deadline exceeded")
            data = chunk if isinstance(chunk, bytes) else str(chunk).encode()
            if not data:
                continue  # a zero-length HTTP chunk would terminate the stream early
            if http10:
                writer.write(data)
            else:
                writer.write(f"{len(data):x}\r\n".encode("latin1") + data + b"\r\n")
            await writer.drain()
        if not http10:
            writer.write(b"0\r\n\r\n")
            await writer.drain()

    @classmethod
    def _encode_response(
        cls, status: int, payload: Any, content_type: str = "application/json", *,
        keep_alive: bool = False, extra_headers: Optional[Dict[str, str]] = None,
    ) -> bytes:
        if content_type == "application/json":
            body = json.dumps(payload, default=str).encode()
        elif isinstance(payload, bytes):
            body = payload
        else:
            body = str(payload).encode()
        connection = "keep-alive" if keep_alive else "close"
        head = (
            f"HTTP/1.1 {status} {_STATUS_PHRASES.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{cls._extra_header_lines(extra_headers)}"
            f"Connection: {connection}\r\n\r\n"
        )
        return head.encode("latin1") + body

    def _deadline_for(self, headers: Dict[str, str]) -> Tuple[Optional[float], bool]:
        """Absolute monotonic deadline for a request: the client's
        ``X-Request-Deadline-Ms`` (clipped to ``max_deadline_ms``), else the
        server default. Returns ``(deadline, explicit)`` — only an explicit
        client deadline also bounds a streaming response body."""
        raw = headers.get(DEADLINE_HEADER)
        explicit = raw is not None
        if explicit:
            try:
                ms = float(raw)
            except ValueError:
                raise HTTPError(400, f"malformed {DEADLINE_HEADER} header: {raw!r}")
        else:
            ms = self.default_deadline_ms
        if ms is not None and self.max_deadline_ms is not None:
            ms = min(ms, self.max_deadline_ms)
        if ms is None:
            return None, False
        return time.monotonic() + ms / 1000.0, explicit

    def _inc(self, counter: str) -> None:
        if self.metrics is not None and hasattr(self.metrics, "inc"):
            self.metrics.inc(counter)

    def _shed_headers(self) -> Dict[str, str]:
        return {"Retry-After": str(self.retry_after_s)}

    async def dispatch(self, method: str, path: str, body: bytes, headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any, str]:
        """Route a request; usable directly by tests (in-process 'test client').
        ``path`` may carry a query string (``/metrics?format=prometheus``)."""
        status, payload, content_type, _, _ = await self._dispatch_full(method, path, body, headers)
        return status, payload, content_type

    async def dispatch_with_headers(
        self, method: str, path: str, body: bytes = b"", headers: Optional[Dict[str, str]] = None
    ) -> Tuple[int, Any, str, Dict[str, str]]:
        """:meth:`dispatch` plus the response's extra headers — the serverless
        adapter uses this so ``X-Request-Id``/``Retry-After`` survive the
        event bridge."""
        status, payload, content_type, extra, _ = await self._dispatch_full(method, path, body, headers)
        return status, payload, content_type, extra

    async def _dispatch_full(
        self, method: str, path: str, body: bytes, headers: Optional[Dict[str, str]] = None
    ) -> Tuple[int, Any, str, Dict[str, str], Optional[float]]:
        """Full dispatch: request-id binding, admission control, deadline
        propagation, then the handler. Returns ``(status, payload,
        content_type, extra_headers, stream_deadline)`` — the last element is
        the absolute deadline to apply to a streaming body (set only when the
        client sent one explicitly)."""
        start = time.perf_counter()
        headers = headers or {}
        path, _, raw_query = path.partition("?")
        query = dict(urllib.parse.parse_qsl(raw_query)) if raw_query else {}
        # request-id contract (docs/observability.md): honor an inbound
        # X-Request-Id (sanitized — a raw echo of client bytes would be a
        # header-injection vector), generate otherwise, echo on EVERY response
        # — errors and sheds included
        rid = sanitize_request_id(headers.get(REQUEST_ID_HEADER)) or new_request_id()
        # multi-tenant QoS (serving/tenancy.py): tenant identity + priority
        # tier ride contextvars like the request id. Requests with none of the
        # three headers skip all of it — the zero-cost-off contract.
        tenant: Optional[str] = None
        priority: Optional[int] = None
        priority_error: Optional[str] = None
        if (
            TENANT_HEADER in headers
            or AUTHORIZATION_HEADER in headers
            or PRIORITY_HEADER in headers
        ):
            tenant = resolve_tenant(headers, active_registry())
            raw_priority = headers.get(PRIORITY_HEADER)
            if raw_priority is not None:
                try:
                    priority = parse_priority(raw_priority)
                except ValueError as exc:
                    priority_error = str(exc)
        tracer = self.tracer
        trace = tracer.start(method, path, rid) if tracer is not None else None
        if trace is not None:
            if tenant is not None:
                trace.tenant = tenant
            if priority is not None:
                trace.priority = priority_name(priority)
        bind_tokens = _bind_request(rid, trace)
        tenant_tokens = _bind_tenant(tenant, priority)
        query_token = request_query.set(query)
        extra: Dict[str, str] = {"X-Request-Id": rid}
        stream_deadline: Optional[float] = None
        if trace is not None:
            trace.event("http.accept", body_bytes=len(body))
        try:
            handler, metrics_route = self._resolve(method, path)
            if metrics_route is None:
                metrics_route = f"{method} {path}"
            if handler is None:
                if any(p == path for (_, p) in self._routes):
                    # bound the label set: arbitrary method tokens must not mint routes
                    if method not in ("GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS", "PATCH"):
                        metrics_route = "<unmatched>"
                    result = 405, {"detail": f"method {method} not allowed for {path}"}, "application/json"
                else:
                    # unmatched paths share one metrics label — per-path labels would let
                    # a scanner grow the route table (and snapshot) without bound
                    metrics_route = "<unmatched>"
                    result = 404, {"detail": f"no route for {path}"}, "application/json"
            elif priority_error is not None:
                # an explicit bad X-Priority is a usage error, not something
                # to silently serve at the wrong tier
                result = 400, {"detail": priority_error}, "application/json"
            elif self.draining and (method, path) not in self._drain_exempt:
                # readiness is off: the load balancer should already be routing
                # around us, so anything still arriving gets a fast 503 + hint
                self._inc("shed_draining")
                extra.update(self._shed_headers())
                if trace is not None:
                    trace.event("http.shed", reason="draining")
                result = 503, {"detail": "server is draining"}, "application/json"
            elif self.max_inflight is not None and self.inflight >= self.max_inflight:
                # admission control: shed NOW with 429 instead of queueing — a
                # bounded queue keeps admitted-request latency bounded, and
                # Retry-After tells well-behaved clients when to come back
                self._inc("shed_inflight")
                extra.update(self._shed_headers())
                if trace is not None:
                    trace.event("http.shed", reason="inflight_cap")
                result = (
                    429,
                    {"detail": f"server at capacity ({self.max_inflight} requests in flight)"},
                    "application/json",
                )
            else:
                try:
                    deadline, explicit = self._deadline_for(headers)
                except HTTPError as exc:
                    result = exc.status, {"detail": exc.detail}, "application/json"
                else:
                    if explicit and deadline is not None:
                        stream_deadline = deadline
                    token = request_deadline.set(deadline)
                    self._inflight += 1
                    try:
                        timeout = remaining_s(deadline)
                        if timeout is not None and timeout <= 0:
                            # born expired (e.g. X-Request-Deadline-Ms: 0 or negative):
                            # shed before the handler runs at all
                            raise DeadlineExceeded("deadline expired before dispatch")
                        result = await asyncio.wait_for(handler(body), timeout)
                    except HTTPError as exc:
                        extra.update(exc.headers)
                        result = exc.status, {"detail": exc.detail}, "application/json"
                    except QueueFullError as exc:
                        # an admission queue deeper in the stack (micro-batcher or
                        # continuous engine) is full — same shed contract as ours.
                        # A TENANT-bucket shed is stamped distinctly and its
                        # Retry-After is the bucket's actual refill time, not
                        # the server's fixed hint (docs/serving.md
                        # "Multi-tenant QoS")
                        if isinstance(exc, TenantThrottled):
                            self._inc("shed_tenant_limit")
                            shed_reason = "tenant_limit"
                        else:
                            self._inc("shed_queue_full")
                            shed_reason = "queue_full"
                        extra.update({"Retry-After": str(exc.retry_after_s)})
                        if trace is not None:
                            trace.event("http.shed", reason=shed_reason)
                        result = 429, {"detail": exc.detail}, "application/json"
                    except (asyncio.TimeoutError, DeadlineExceeded) as exc:
                        # the deadline fired: wait_for has cancelled the handler (its
                        # pending batcher future is dropped and the queued work shed at
                        # the next dispatch), so resources are reclaimed, not leaked
                        self._inc("deadline_timeouts")
                        extra.update(self._shed_headers())
                        if trace is not None:
                            trace.event("http.shed", reason="deadline")
                        detail = str(exc) or "request deadline exceeded"
                        result = 503, {"detail": detail}, "application/json"
                    except Exception as exc:  # pragma: no cover - defensive
                        logger.exception("handler error")
                        result = 500, {"detail": f"{type(exc).__name__}: {exc}"}, "application/json"
                    finally:
                        self._inflight -= 1
                        request_deadline.reset(token)
            status, payload = result[0], result[1]
            if trace is not None:
                if hasattr(payload, "__aiter__"):
                    # the handler returned a stream: the trace must outlive this
                    # method — the wrapper records per-chunk events and finishes
                    # the timeline when the stream ends (or aborts)
                    result = (status, self._traced_stream(payload, trace, status), result[2])
                else:
                    detail = payload.get("detail") if isinstance(payload, dict) and status >= 400 else None
                    tracer.finish(trace, status, detail)
            if self.metrics is not None:
                self.metrics.record(metrics_route, status, time.perf_counter() - start)
            if self.access_log:
                logger.info(
                    f"{method} {path} {status} "
                    f"{round((time.perf_counter() - start) * 1e3, 2)}ms rid={rid}"
                )
            return (*result, extra, stream_deadline)
        finally:
            request_query.reset(query_token)
            _unbind_tenant(tenant_tokens)
            _unbind_request(bind_tokens)

    def _traced_stream(self, payload: Any, trace: Any, status: int):
        """Wrap a streaming body so its trace finishes when the STREAM does
        (the handler returned long before the last chunk): one event per HTTP
        chunk, terminal status on exhaustion/abort, and the wrapped payload's
        ``aclose`` still runs — the producer-release contract is preserved."""
        tracer = self.tracer

        async def wrapped():
            try:
                async for chunk in payload:
                    trace.event(
                        "http.stream_chunk",
                        bytes=len(chunk) if isinstance(chunk, (bytes, str)) else 0,
                    )
                    yield chunk
            except BaseException as exc:
                tracer.finish(trace, status, f"stream aborted: {type(exc).__name__}")
                raise
            else:
                tracer.finish(trace, status)
            finally:
                # `async for` does not aclose an early-exited iterator; the
                # server acloses THIS wrapper, so forward the release
                closer = getattr(payload, "aclose", None)
                if closer is not None:
                    try:
                        await closer()
                    except Exception:  # pragma: no cover - defensive
                        pass

        return wrapped()

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    # idle timeout applies only to waiting for the NEXT request line;
                    # an in-flight slow body read is never cancelled mid-request
                    request_line = await asyncio.wait_for(reader.readline(), KEEPALIVE_IDLE_S)
                except asyncio.TimeoutError:
                    break  # idle keep-alive connection: close quietly
                if not request_line:
                    break
                request = await self._read_request(reader, request_line)
                if request is None:
                    break
                method, path, body, keep_alive, http10, req_headers = request
                status, payload, content_type, extra, stream_deadline = await self._dispatch_full(
                    method, path, body, req_headers
                )
                if self.draining:
                    # a drain must converge: no new requests down this connection
                    keep_alive = False
                if hasattr(payload, "__aiter__"):
                    # streaming handler: one HTTP chunk per item (1.0 peers get an
                    # unframed close-delimited body)
                    keep_alive = keep_alive and not http10
                    writer.write(self._encode_stream_head(
                        status, content_type, keep_alive=keep_alive, http10=http10, extra_headers=extra
                    ))
                    self._streams += 1
                    try:
                        await self._write_stream(writer, payload, http10=http10, deadline=stream_deadline)
                    except DeadlineExceeded:
                        # explicit client deadline hit mid-stream: truncate at
                        # this chunk boundary; the finally below acloses the
                        # payload, which releases the producer's engine slot
                        self._inc("stream_deadline_truncations")
                        logger.warning(f"stream truncated at client deadline: {method} {path}")
                        break
                    except Exception as exc:
                        # predictor failure mid-stream, or the client went away
                        # (ConnectionResetError from drain): the response is already
                        # underway, so truncate the stream and drop the connection
                        logger.warning(f"stream aborted: {type(exc).__name__}: {exc}")
                        break
                    finally:
                        self._streams -= 1
                        closer = getattr(payload, "aclose", None)
                        if closer is not None:
                            try:
                                await closer()  # release the producer promptly
                            except Exception:
                                pass
                else:
                    writer.write(self._encode_response(
                        status, payload, content_type, keep_alive=keep_alive, extra_headers=extra
                    ))
                    await writer.drain()
                if not keep_alive:
                    break
        except (ValueError, asyncio.IncompleteReadError) as exc:
            try:
                writer.write(self._encode_response(400, {"detail": str(exc)}))
                await writer.drain()
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    # ------------------------------------------------------------------ drain

    def begin_drain(self) -> None:
        """Flip readiness off and stop accepting new work: ``GET /health``
        reports ``ready: false`` (503), every non-exempt route sheds with 503 +
        ``Retry-After``, and the listening socket closes so a load balancer's
        next connection attempt fails over to a healthy replica. In-flight
        requests and streams keep running — :meth:`shutdown` waits for them."""
        if not self.draining:
            self.draining = True
            logger.info("drain started: readiness off, shedding new requests")
        if self._server is not None:
            self._server.close()

    async def shutdown(self, drain_timeout_s: Optional[float] = None) -> None:
        """Graceful drain: stop admitting, wait for in-flight requests and live
        streams to finish (bounded by ``drain_timeout_s``), then stop
        ``serve()``. Wired to SIGTERM by :meth:`serve`, so a rolling restart on
        a TPU slice finishes live decodes instead of dropping them."""
        self.begin_drain()
        timeout = self.drain_timeout_s if drain_timeout_s is None else drain_timeout_s
        deadline = time.monotonic() + timeout
        while (self._inflight > 0 or self._streams > 0) and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if self._inflight > 0 or self._streams > 0:
            logger.warning(
                f"drain timeout after {timeout:.1f}s with {self._inflight} requests and "
                f"{self._streams} streams still in flight; exiting anyway"
            )
        else:
            logger.info("drain complete: all in-flight work finished")
        if self.on_drained is not None:
            try:
                self.on_drained()  # the app closes its batching engines
            except Exception:  # pragma: no cover - defensive
                logger.exception("on_drained hook failed")
        if self._stop_serving is not None:
            self._stop_serving.set()

    async def serve(self, host: str = "127.0.0.1", port: int = 8000, *, reuse_port: bool = False) -> None:
        # reuse_port lets N worker processes share one listening port (the kernel
        # load-balances accepts) — the `serve --workers N` multi-process mode
        self._server = await asyncio.start_server(self._on_connection, host, port, reuse_port=reuse_port or None)
        self._stop_serving = asyncio.Event()
        loop = asyncio.get_running_loop()
        sigterm_installed = False
        try:
            loop.add_signal_handler(
                signal.SIGTERM, lambda: asyncio.ensure_future(self.shutdown())
            )
            sigterm_installed = True
        except (NotImplementedError, RuntimeError, ValueError):
            # non-main thread, or a platform without signal-handler support:
            # drain stays reachable programmatically via shutdown()
            pass
        logger.info(f"serving on http://{host}:{port}")
        try:
            async with self._server:
                serve_task = asyncio.create_task(self._server.serve_forever())
                stop_task = asyncio.create_task(self._stop_serving.wait())
                try:
                    done, _ = await asyncio.wait(
                        {serve_task, stop_task}, return_when=asyncio.FIRST_COMPLETED
                    )
                    if stop_task not in done and self.draining:
                        # begin_drain() closed the listener, which cancels
                        # serve_forever — but in-flight work is still draining;
                        # shutdown() sets the stop event once it finishes
                        await stop_task
                    # surface an unexpected accept-loop crash (a drain-stopped
                    # serve_forever is cancelled, not failed)
                    if serve_task in done and not serve_task.cancelled() and serve_task.exception():
                        raise serve_task.exception()
                finally:
                    for task in (serve_task, stop_task):
                        task.cancel()
                    await asyncio.gather(serve_task, stop_task, return_exceptions=True)
        finally:
            if sigterm_installed:
                loop.remove_signal_handler(signal.SIGTERM)

    def run(self, host: str = "127.0.0.1", port: int = 8000, *, reuse_port: bool = False) -> None:
        try:
            asyncio.run(self.serve(host, port, reuse_port=reuse_port))
        except KeyboardInterrupt:  # pragma: no cover
            logger.info("server stopped")


class HTTPError(Exception):
    """Raise inside a handler to produce a non-200 JSON response.

    ``headers`` ride onto the response head — the 429/503 shed paths use it for
    ``Retry-After``."""

    def __init__(self, status: int, detail: str, headers: Optional[Dict[str, str]] = None):
        super().__init__(detail)
        self.status = status
        self.detail = detail
        self.headers: Dict[str, str] = headers or {}
