"""Minimal asyncio HTTP/1.1 server for model serving.

Replaces the reference's FastAPI/uvicorn dependency (unionml/fastapi.py) with a
self-contained server: request-line + header parsing, Content-Length bodies, JSON
responses, HTTP/1.1 keep-alive (persistent connections with an idle timeout — a
benchmark client reusing one connection pays the TCP/loopback handshake once, not
per request), graceful shutdown. Deliberately small — the serving surface is four
routes — and dependency-free so the serving container stays lean on TPU VMs.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from unionml_tpu._logging import logger

Handler = Callable[[bytes], Awaitable[Tuple[int, Any, str]]]

_STATUS_PHRASES = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    500: "Internal Server Error",
}

MAX_BODY_BYTES = 64 * 1024 * 1024
KEEPALIVE_IDLE_S = 75.0


class HTTPServer:
    """Route table + asyncio socket loop."""

    def __init__(self) -> None:
        self._routes: Dict[Tuple[str, str], Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        #: optional sink with a ``record(route, status, latency_s)`` method
        #: (:class:`unionml_tpu.serving.metrics.ServingMetrics`)
        self.metrics: Any = None

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._routes[(method.upper(), path)] = handler

    async def _read_request(
        self, reader: asyncio.StreamReader, request_line: Optional[bytes] = None
    ) -> Optional[Tuple[str, str, bytes, bool, bool]]:
        if request_line is None:
            request_line = await reader.readline()
        if not request_line:
            return None
        try:
            method, target, version = request_line.decode("latin1").split(" ", 2)
        except ValueError:
            raise ValueError("malformed request line")
        path = target.split("?", 1)[0]

        content_length = 0
        # HTTP/1.1 defaults to persistent connections; 1.0 must opt in
        http10 = "1.0" in version
        keep_alive = not http10
        wants_close = False
        while True:
            header_line = await reader.readline()
            if header_line in (b"\r\n", b"\n", b""):
                break
            name, _, value = header_line.decode("latin1").partition(":")
            name = name.strip().lower()
            if name == "content-length":
                content_length = int(value.strip())
            elif name == "connection":
                # the value is a comma-separated token list ("close, TE"); an
                # explicit close wins over everything, including later headers
                tokens = {t.strip().lower() for t in value.split(",")}
                if "close" in tokens:
                    keep_alive = False
                    wants_close = True
                elif "keep-alive" in tokens and not wants_close:
                    keep_alive = True
        if content_length > MAX_BODY_BYTES:
            raise ValueError("request body too large")
        body = await reader.readexactly(content_length) if content_length else b""
        return method.upper(), path, body, keep_alive, http10

    @staticmethod
    def _encode_stream_head(status: int, content_type: str, *, keep_alive: bool, http10: bool) -> bytes:
        """Response head for a streaming body. HTTP/1.0 peers cannot parse chunked
        framing, so they get an unframed close-delimited body instead."""
        connection = "keep-alive" if (keep_alive and not http10) else "close"
        framing = "" if http10 else "Transfer-Encoding: chunked\r\n"
        return (
            f"HTTP/1.1 {status} {_STATUS_PHRASES.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"{framing}"
            f"Connection: {connection}\r\n\r\n"
        ).encode("latin1")

    @staticmethod
    async def _write_stream(writer: asyncio.StreamWriter, payload: Any, *, http10: bool) -> None:
        """Emit an async-iterator payload, draining per chunk so each arrives as
        soon as it is produced: chunked transfer encoding for HTTP/1.1, raw bytes
        delimited by connection close for HTTP/1.0."""
        async for chunk in payload:
            data = chunk if isinstance(chunk, bytes) else str(chunk).encode()
            if not data:
                continue  # a zero-length HTTP chunk would terminate the stream early
            if http10:
                writer.write(data)
            else:
                writer.write(f"{len(data):x}\r\n".encode("latin1") + data + b"\r\n")
            await writer.drain()
        if not http10:
            writer.write(b"0\r\n\r\n")
            await writer.drain()

    @staticmethod
    def _encode_response(
        status: int, payload: Any, content_type: str = "application/json", *, keep_alive: bool = False
    ) -> bytes:
        if content_type == "application/json":
            body = json.dumps(payload, default=str).encode()
        elif isinstance(payload, bytes):
            body = payload
        else:
            body = str(payload).encode()
        connection = "keep-alive" if keep_alive else "close"
        head = (
            f"HTTP/1.1 {status} {_STATUS_PHRASES.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {connection}\r\n\r\n"
        )
        return head.encode("latin1") + body

    async def dispatch(self, method: str, path: str, body: bytes) -> Tuple[int, Any, str]:
        """Route a request; usable directly by tests (in-process 'test client')."""
        start = time.perf_counter()
        handler = self._routes.get((method, path))
        metrics_route = f"{method} {path}"
        if handler is None:
            if any(p == path for (_, p) in self._routes):
                # bound the label set: arbitrary method tokens must not mint routes
                if method not in ("GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS", "PATCH"):
                    metrics_route = "<unmatched>"
                result = 405, {"detail": f"method {method} not allowed for {path}"}, "application/json"
            else:
                # unmatched paths share one metrics label — per-path labels would let
                # a scanner grow the route table (and snapshot) without bound
                metrics_route = "<unmatched>"
                result = 404, {"detail": f"no route for {path}"}, "application/json"
        else:
            try:
                result = await handler(body)
            except HTTPError as exc:
                result = exc.status, {"detail": exc.detail}, "application/json"
            except Exception as exc:  # pragma: no cover - defensive
                logger.exception("handler error")
                result = 500, {"detail": f"{type(exc).__name__}: {exc}"}, "application/json"
        if self.metrics is not None:
            self.metrics.record(metrics_route, result[0], time.perf_counter() - start)
        return result

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    # idle timeout applies only to waiting for the NEXT request line;
                    # an in-flight slow body read is never cancelled mid-request
                    request_line = await asyncio.wait_for(reader.readline(), KEEPALIVE_IDLE_S)
                except asyncio.TimeoutError:
                    break  # idle keep-alive connection: close quietly
                if not request_line:
                    break
                request = await self._read_request(reader, request_line)
                if request is None:
                    break
                method, path, body, keep_alive, http10 = request
                status, payload, content_type = await self.dispatch(method, path, body)
                if hasattr(payload, "__aiter__"):
                    # streaming handler: one HTTP chunk per item (1.0 peers get an
                    # unframed close-delimited body)
                    keep_alive = keep_alive and not http10
                    writer.write(self._encode_stream_head(status, content_type, keep_alive=keep_alive, http10=http10))
                    try:
                        await self._write_stream(writer, payload, http10=http10)
                    except Exception as exc:
                        # predictor failure mid-stream, or the client went away
                        # (ConnectionResetError from drain): the response is already
                        # underway, so truncate the stream and drop the connection
                        logger.warning(f"stream aborted: {type(exc).__name__}: {exc}")
                        break
                    finally:
                        closer = getattr(payload, "aclose", None)
                        if closer is not None:
                            try:
                                await closer()  # release the producer promptly
                            except Exception:
                                pass
                else:
                    writer.write(self._encode_response(status, payload, content_type, keep_alive=keep_alive))
                    await writer.drain()
                if not keep_alive:
                    break
        except (ValueError, asyncio.IncompleteReadError) as exc:
            try:
                writer.write(self._encode_response(400, {"detail": str(exc)}))
                await writer.drain()
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def serve(self, host: str = "127.0.0.1", port: int = 8000, *, reuse_port: bool = False) -> None:
        # reuse_port lets N worker processes share one listening port (the kernel
        # load-balances accepts) — the `serve --workers N` multi-process mode
        self._server = await asyncio.start_server(self._on_connection, host, port, reuse_port=reuse_port or None)
        logger.info(f"serving on http://{host}:{port}")
        async with self._server:
            await self._server.serve_forever()

    def run(self, host: str = "127.0.0.1", port: int = 8000, *, reuse_port: bool = False) -> None:
        try:
            asyncio.run(self.serve(host, port, reuse_port=reuse_port))
        except KeyboardInterrupt:  # pragma: no cover
            logger.info("server stopped")


class HTTPError(Exception):
    """Raise inside a handler to produce a non-200 JSON response."""

    def __init__(self, status: int, detail: str):
        super().__init__(detail)
        self.status = status
        self.detail = detail
