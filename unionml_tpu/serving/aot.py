"""AOT program store: serialized XLA executables preloaded at serve start.

BENCH_ALL.json records an 87.6 s BERT-base compile against a 0.14 s train
step — and every freshly started server, every ``scale_to`` scale-up replica,
and every serverless cold start used to pay that compile before its first
token. JAX's persistent compilation cache (compile_cache.py) removes the
*XLA-compile* cost of a re-run but still re-traces, re-lowers, and round-trips
every program through the compiler's cache machinery; nothing in the serving
stack ahead-of-time serialized the generator's *executables* so a cold process
could skip the whole pipeline.

This module is that missing layer:

- :class:`ProgramStore` — a directory of serialized executables
  (``jax.experimental.serialize_executable``), one entry per
  (program, backend, mesh, config, argument-signature) key. Entries carry a
  human-readable meta sidecar; corrupted or stale entries are skipped (and
  deleted) with a warning, never crash the serving path.
- :class:`AOTFunction` — a drop-in wrapper for a ``jax.jit`` binding that
  resolves every distinct call signature **load-before-compile**: an
  in-memory executable, else a store entry (deserialize, ~ms), else
  ``lower().compile()`` — whose result is serialized back into the store so
  the *next* cold process loads it. Backends whose executables cannot be
  serialized degrade to plain jit behavior with a single warning.

Keying: executables are pinned to the devices they were compiled for (the
PjRt device assignment rides the serialized artifact), so the key covers the
jax/jaxlib versions, backend platform, device kinds **and ids**, the mesh's
axis names + shape, the generator's module/generation configs (quantize and
kv-cache dtype included), and the abstract argument signature. A restarted
server, a serverless warm pool, or a ``scale_to`` replica landing on a
previously-used submesh all hit; a never-seen topology misses once, compiles,
and persists for every process after it. ``serve --aot-preload [DIR]``
(``UNIONML_TPU_AOT_PRELOAD``) turns the store on fleet-wide; see
docs/serving.md "Cold start and AOT preload".
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional, Tuple

from unionml_tpu._logging import logger
from unionml_tpu.serving.metrics import LatencyWindow

__all__ = ["AOTFunction", "ProgramStore", "resolve_store"]

#: default store location (next to the persistent XLA cache's default)
_DEFAULT_DIR = "~/.cache/unionml_tpu/aot"

#: store format version: bumping it orphans (never breaks) old entries
_FORMAT = 1


def backend_context() -> Dict[str, Any]:
    """The process-level key parts every entry depends on: serialized
    executables are only loadable by the jax/jaxlib/backend that wrote them."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:  # pragma: no cover - jaxlib always rides jax
        jaxlib_version = "unknown"
    devices = jax.devices()
    return {
        "format": _FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": devices[0].platform,
        "device_kind": devices[0].device_kind,
        "device_count": len(devices),
    }


def mesh_context(mesh: Optional[Any]) -> Dict[str, Any]:
    """Mesh key parts: axis names, per-axis extents, and the device ids —
    a deserialized executable re-binds devices BY ID, so an entry compiled
    for one submesh must never load onto a different one."""
    if mesh is None:
        return {"mesh": None}
    return {
        "mesh": {
            "axis_names": list(mesh.axis_names),
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
            "device_ids": [int(d.id) for d in mesh.devices.flat],
        }
    }


def _leaf_signature(leaf: Any) -> Tuple:
    """One argument leaf's contribution to the entry key: shape/dtype/weak-type
    for arrays, the bare Python type for scalar arguments (their *values* are
    dynamic — jit compiles one program for every ``skip=`` int, not one per
    value)."""
    if isinstance(leaf, (bool, int, float)):
        return ("py", type(leaf).__name__)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(int(s) for s in shape), str(dtype), bool(getattr(leaf, "weak_type", False)))
    return ("opaque", type(leaf).__name__)


class ProgramStore:
    """A directory of AOT-serialized executables keyed by content digests.

    Layout: ``<root>/<digest>.aotx`` (the pickled
    ``serialize_executable.serialize`` payload) plus ``<root>/<digest>.json``
    (a human-readable meta sidecar: program name, context, signature — the
    debugging surface ``docs/serving.md`` documents). Writes are atomic
    (tmp + rename) so a killed process never leaves a torn entry; reads that
    fail for ANY reason delete the entry and report a miss — the serving path
    then compiles exactly as it would have without the store.

    Counters feed ``stats()["aot"]`` on the continuous engine (and ``/metrics``
    through it): programs loaded/compiled/serialized plus load/compile latency
    windows — the before/after the ``cold_start`` bench lane pins.
    """

    def __init__(self, root: Optional[str] = None, *, context: Optional[Dict[str, Any]] = None):
        path = os.path.abspath(os.path.expanduser(root or _DEFAULT_DIR))
        self.disabled = False
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as exc:
            # an unwritable dir must degrade to plain-jit serving, not crash it
            logger.warning(f"AOT program store disabled (cannot create {path}: {exc})")
            self.disabled = True
        self.root = path
        self._context = dict(context or {})
        self._context.update(backend_context())
        self._lock = threading.Lock()
        self.programs_loaded = 0
        self.programs_compiled = 0
        self.programs_serialized = 0
        self.load_failures = 0
        self.serialize_failures = 0
        self.load_ms = LatencyWindow()
        self.compile_ms = LatencyWindow()
        self._serialize_unsupported = False

    # ------------------------------------------------------------------ keys

    def context_prefix(self, program: str, context: Dict[str, Any]) -> str:
        """The per-(program, context) half of the entry key, serialized once —
        :class:`AOTFunction` caches it so the per-call work is just the
        argument signature's digest (the decode dispatch path runs through
        this on every engine iteration)."""
        return json.dumps(
            {"store": self._context, "program": program, "context": context},
            sort_keys=True,
            default=repr,
        )

    @staticmethod
    def key_for(prefix: str, signature: Any) -> str:
        return hashlib.sha256((prefix + "|" + repr(signature)).encode()).hexdigest()

    def entry_key(self, program: str, context: Dict[str, Any], signature: Any) -> str:
        """Stable digest over (store context, program name, caller context,
        argument signature). Any mismatch — a new jax version, a different
        mesh, a resized bucket — lands on a different digest, so stale
        entries are *skipped*, never mistakenly loaded."""
        return self.key_for(self.context_prefix(program, context), signature)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.aotx")

    def has(self, key: str) -> bool:
        return not self.disabled and os.path.exists(self._path(key))

    # ------------------------------------------------------------------ io

    def load(self, key: str) -> Optional[Tuple]:
        """The pickled serialization payload for ``key``, or ``None`` on a
        miss. A present-but-unreadable entry (torn write, version skew inside
        the pickle) is deleted and reported as a miss with a warning — the
        caller compiles, then overwrites it with a good entry."""
        if self.disabled:
            return None
        path = self._path(key)
        try:
            with open(path, "rb") as fh:
                payload = pickle.loads(fh.read())
            if not (isinstance(payload, tuple) and len(payload) == 3):
                raise ValueError(f"malformed AOT entry (expected a 3-tuple, got {type(payload).__name__})")
            return payload
        except FileNotFoundError:
            return None
        except Exception as exc:
            with self._lock:
                self.load_failures += 1
            logger.warning(f"corrupted AOT entry {key[:12]}… ({exc}); deleting and recompiling")
            self._discard(key)
            return None

    def _discard(self, key: str) -> None:
        for suffix in (".aotx", ".json"):
            try:
                os.remove(os.path.join(self.root, key + suffix))
            except OSError:
                pass

    def save(self, key: str, payload: Tuple, meta: Dict[str, Any]) -> bool:
        """Persist one serialized executable atomically (payload first, meta
        sidecar after — a reader never sees meta without its entry)."""
        if self.disabled:
            return False
        path = self._path(key)
        try:
            blob = pickle.dumps(payload)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
            meta_tmp = os.path.join(self.root, key + f".json.tmp.{os.getpid()}")
            with open(meta_tmp, "w") as fh:
                json.dump({"store": self._context, **meta}, fh, indent=2, sort_keys=True, default=repr)
            os.replace(meta_tmp, os.path.join(self.root, key + ".json"))
        except Exception as exc:
            with self._lock:
                self.serialize_failures += 1
            logger.warning(f"could not persist AOT entry {key[:12]}… ({exc})")
            return False
        with self._lock:
            self.programs_serialized += 1
        return True

    def entries(self) -> "list[Dict[str, Any]]":
        """The meta sidecars on disk (tests and operators introspect these)."""
        out = []
        if self.disabled:
            return out
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name)) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError):
                continue
            meta["key"] = name[: -len(".json")]
            out.append(meta)
        return out

    def entry_count(self) -> int:
        if self.disabled:
            return 0
        try:
            return sum(1 for name in os.listdir(self.root) if name.endswith(".aotx"))
        except OSError:
            return 0

    # ------------------------------------------------------------------ telemetry

    def note_loaded(self, seconds: float) -> None:
        """Count one store deserialize (the cold-start fast path)."""
        self.load_ms.observe(seconds)
        with self._lock:
            self.programs_loaded += 1

    def note_compiled(self, seconds: float) -> None:
        """Count one lower+compile (the store-miss slow path)."""
        self.compile_ms.observe(seconds)
        with self._lock:
            self.programs_compiled += 1

    def note_load_failure(self, program: str, key: str, exc: BaseException) -> None:
        """A payload that unpickled but would not rebind in this process
        (device set changed under the same ids, jaxlib skew inside the bytes)
        is corrupt for this process: drop it so the caller compiles."""
        with self._lock:
            self.load_failures += 1
        logger.warning(f"AOT entry for {program!r} failed to deserialize ({exc}); recompiling")
        self._discard(key)

    def note_serialize_unsupported(self, program: str, exc: BaseException) -> None:
        """One warning per store when the backend cannot serialize executables
        (enabling the store there is never incorrect, only useless)."""
        with self._lock:
            self.serialize_failures += 1
            if self._serialize_unsupported:
                return
            self._serialize_unsupported = True
        logger.warning(
            f"this backend cannot serialize compiled executables ({exc}); AOT "
            f"preload degrades to plain jit compiles (first seen on {program!r})"
        )

    def stats(self) -> Dict[str, Any]:
        """``stats()["aot"]`` payload: ints + latency windows only (the
        ``/metrics`` no-None-gauge contract)."""
        with self._lock:
            out: Dict[str, Any] = {
                "programs_loaded": self.programs_loaded,
                "programs_compiled": self.programs_compiled,
                "programs_serialized": self.programs_serialized,
                "load_failures": self.load_failures,
                "serialize_failures": self.serialize_failures,
            }
        out["entries"] = self.entry_count()
        out["load_ms"] = self.load_ms.snapshot()
        out["compile_ms"] = self.compile_ms.snapshot()
        return out


def resolve_store(aot: Any, *, context: Optional[Dict[str, Any]] = None) -> Optional[ProgramStore]:
    """Normalize an ``aot=`` knob: a :class:`ProgramStore` passes through, a
    path string builds one, ``True`` resolves the env export (default
    location if the export is a bare flag), ``None`` consults
    ``UNIONML_TPU_AOT_PRELOAD`` (the serve CLI's early export), and ``False``
    is off. A store that failed to initialize resolves to ``None`` so the
    caller serves plain-jit."""
    if aot is False:
        return None
    if isinstance(aot, ProgramStore):
        return None if aot.disabled else aot
    if aot is None or aot is True:
        from unionml_tpu.defaults import serve_aot_preload

        path = serve_aot_preload()
        if path is None:
            return None
    else:
        path = os.fspath(aot)
    store = ProgramStore(path, context=context)
    return None if store.disabled else store


class AOTFunction:
    """Load-before-compile dispatch for one ``jax.jit`` binding.

    Call-compatible with the wrapped binding (static arguments included —
    they fold into the entry key and are omitted from the executable call,
    exactly as jit omits them from the traced signature). Per distinct
    signature, resolution order is: in-memory executable → store entry
    (deserialize) → ``lower().compile()`` + serialize back into the store.
    Donation semantics ride the executable itself (input-output aliasing is
    baked in at compile time), so wrapped and unwrapped calls are
    bit-identical — the contract the AOT==JIT exactness tests pin.

    A loaded executable that rejects its inputs (sharding/layout skew the key
    did not capture) falls back to a fresh compile for that signature — the
    check happens before execution, so no donated buffer is lost.
    """

    def __init__(
        self,
        jit_fn: Any,
        program: str,
        store: ProgramStore,
        context: Dict[str, Any],
        *,
        static_argnums: Tuple[int, ...] = (),
        static_argnames: Tuple[str, ...] = (),
    ):
        self._jit = jit_fn
        self.program = program
        self.store = store
        self._context = dict(context)
        self._static_argnums = tuple(static_argnums)
        self._static_argnames = tuple(static_argnames)
        #: the context half of the key, serialized once — per call only the
        #: argument signature is hashed (this wrapper sits on the decode
        #: dispatch path, which runs every engine iteration)
        self._key_prefix = store.context_prefix(program, self._context)
        self._exes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    #: in-memory executables per wrapper: real programs have a handful of
    #: signatures (one per bucket/chunk shape), so this only triggers if a
    #: caller generates unbounded shapes — evict FIFO rather than grow forever
    _MAX_EXES = 64

    def _cache_exe_locked(self, key: str, exe: Any) -> None:
        if len(self._exes) >= self._MAX_EXES:
            self._exes.pop(next(iter(self._exes)))
        self._exes[key] = exe

    def _signature(self, args: Tuple, kwargs: Dict[str, Any]):
        import jax

        static_pos = tuple((i, repr(args[i])) for i in self._static_argnums if i < len(args))
        static_kw = tuple(sorted((k, repr(v)) for k, v in kwargs.items() if k in self._static_argnames))
        dyn_args = tuple(a for i, a in enumerate(args) if i not in self._static_argnums)
        dyn_kwargs = {k: v for k, v in kwargs.items() if k not in self._static_argnames}
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        return (
            (static_pos, static_kw, tuple(_leaf_signature(leaf) for leaf in leaves), str(treedef)),
            dyn_args,
            dyn_kwargs,
        )

    def _record_event(self, source: str, ms: float) -> None:
        from unionml_tpu.observability.trace import current_trace

        trace = current_trace()
        if trace is not None:
            trace.event("engine.aot_preload", program=self.program, source=source, ms=round(ms, 3))

    def _load(self, key: str) -> Optional[Any]:
        from jax.experimental import serialize_executable

        payload = self.store.load(key)
        if payload is None:
            return None
        start = time.perf_counter()
        try:
            exe = serialize_executable.deserialize_and_load(*payload)
        except Exception as exc:
            self.store.note_load_failure(self.program, key, exc)
            return None
        elapsed = time.perf_counter() - start
        self.store.note_loaded(elapsed)
        self._record_event("store", elapsed * 1e3)
        return exe

    def _compile(self, key: str, sig: Any, args: Tuple, kwargs: Dict[str, Any]) -> Any:
        from jax.experimental import serialize_executable

        start = time.perf_counter()
        compiled = self._jit.lower(*args, **kwargs).compile()
        elapsed = time.perf_counter() - start
        self.store.note_compiled(elapsed)
        self._record_event("compile", elapsed * 1e3)
        try:
            payload = serialize_executable.serialize(compiled)
        except Exception as exc:
            self.store.note_serialize_unsupported(self.program, exc)
            return compiled
        self.store.save(
            key,
            payload,
            {
                "program": self.program,
                "context": self._context,
                "signature": repr(sig),
                "compile_s": round(elapsed, 3),
            },
        )
        return compiled

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        sig, dyn_args, dyn_kwargs = self._signature(args, kwargs)
        key = ProgramStore.key_for(self._key_prefix, sig)
        exe = self._exes.get(key)
        if exe is None:
            with self._lock:
                exe = self._exes.get(key)
                if exe is None:
                    exe = self._load(key)
                    if exe is None:
                        exe = self._compile(key, sig, args, kwargs)
                    self._cache_exe_locked(key, exe)
        try:
            return exe(*dyn_args, **dyn_kwargs)
        except (ValueError, TypeError) as exc:
            # input validation happens BEFORE execution, so nothing was
            # donated yet — recompile for the actual inputs and replace the
            # in-memory (and on-disk) entry
            logger.warning(
                f"AOT executable for {self.program!r} rejected its inputs "
                f"({type(exc).__name__}: {exc}); recompiling"
            )
            exe = self._compile(key, sig, args, kwargs)
            with self._lock:
                self._cache_exe_locked(key, exe)
            return exe(*dyn_args, **dyn_kwargs)
