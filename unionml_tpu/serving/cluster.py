"""Multi-host fleet serving: a host-0 coordinator over per-process worker fleets.

Everything the serving stack shipped through PR 10 — replica slicing, the
disaggregated prefill/decode handoff, elastic ``scale_to``, SLO-aware and
prefix-affine routing — lived inside ONE Python process, so a fleet could
never outgrow a single host's devices (ROADMAP item 2's "last structural
wall"). This module breaks it:

- **workers** own their local engines: each process builds a
  :class:`~unionml_tpu.serving.replicas.ReplicaSet` (or a single
  :class:`~unionml_tpu.serving.continuous.ContinuousBatcher`) over its OWN
  devices — on a hybrid ICI/DCN mesh
  (:meth:`~unionml_tpu.parallel.mesh.MeshSpec.build_hybrid`, the T5X
  partitioning shape: DCN carries the data/replica axes, ICI the model axes)
  each host keeps exactly the replica submeshes that are local to it
  (``ReplicaSet.build`` is process-aware) — and expose them through a
  loopback control server (:class:`WorkerAgent`);
- **the coordinator** (:class:`FleetCoordinator`) owns routing, admission,
  and scale decisions: it mirrors the engine surface (``submit`` / ``warmup``
  / ``stats`` / ``health`` / ``scale_to`` / ``close``) so the serving app,
  ``/metrics``, ``/healthz`` and ``/debug/fleet`` compose with a multi-host
  fleet exactly as they do with a :class:`ReplicaSet`;
- **the control plane** is plain HTTP over loopback/DCN (newline-delimited
  JSON token streams, binary ``npz`` handoff payloads): out-of-band from the
  jax runtime, so a worker crash breaks one TCP connection — the coordinator
  marks the host dead and routes around it — instead of a collective;
- **jax.distributed** (:mod:`unionml_tpu.distributed`, the bootstrap shared
  with ``job_runner``) gives workers their process identity, and
  ``multihost_utils`` carries the cross-host agreements: process 0's fleet
  config is broadcast so every host provably builds knob-identical engines
  (:func:`distributed.agree`), and control ports are exchanged with
  ``process_allgather`` (:func:`distributed.allgather_ints`).

Routing is the :class:`~unionml_tpu.serving.replicas.ReplicaScheduler` at
HOST granularity: per-submission the coordinator probes every live host for
its token-weighted load, SLO state, and — the fleet-global radix tier — its
ACTUAL cached-prefix length for this prompt, so a multi-turn conversation
lands on the host that already holds its KV. Hosts may carry roles
(``prefill``/``decode``/``mixed``, the ``UNIONML_TPU_HOST_ROLES`` export):
a long prompt prefills on a prefill host and its finished KV pages — the
block-native payload of ``continuous._export_admission`` — cross the wire to
a decode host, token-identical to a single mixed fleet serving it.

Collectives (``agree``/``barrier``/``allgather_ints``) run only during
worker bootstrap and NEVER while holding a lock — one stalled host must
degrade to a dead host, not a fleet-wide deadlock (tpu-lint TPU013, which
this module is the reason for).

Fault tolerance (docs/serving.md "Fault tolerance") is a lifecycle, not a
boolean: a transport failure moves a host ``live → suspect`` (routed around
but re-probed), consecutive probe failures move it to ``dead``, a fresh
rendezvous announce or a successful re-probe moves it to ``probation``, and
probation probes + warmup move it back to ``live``. Idempotent control RPCs
(ping/probe/stats/health) retry with bounded decorrelated jitter before
suspecting anyone; streams that die with zero tokens emitted are retried
once on a sibling host, streams that already emitted terminate with a clean
503-shaped :class:`StreamInterrupted` — never a silent hang. The coordinator
persists a fenced (epoch-stamped) checkpoint and a heartbeat lease in the
rendezvous dir; on lease expiry the lowest-id live worker promotes itself
(:func:`maybe_promote`), and a zombie coordinator's writes are rejected.
Every failure mode is reproducible under a seeded
:class:`~unionml_tpu.serving.faults.FaultPlan`.
"""

from __future__ import annotations

import base64
import io
import json
import math
import os
import random
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.defaults import (
    fleet_dead_after_probes,
    fleet_dir as default_fleet_dir,
    fleet_host_roles,
    fleet_lease_ttl_s,
    fleet_probation_probes,
    fleet_probe_interval_s,
    serve_prefill_threshold,
)
from unionml_tpu.serving.faults import ArmedFaultPlan, FaultPlan
from unionml_tpu.serving.metrics import LatencyWindow
from unionml_tpu.serving.overload import (
    DeadlineExceeded,
    QueueFullError,
    TenantThrottled,
    expired,
    remaining_s,
)
from unionml_tpu.serving.replicas import ReplicaScheduler

__all__ = [
    "FleetCoordinator",
    "HostDied",
    "LocalHost",
    "RemoteHost",
    "StreamInterrupted",
    "WorkerAgent",
    "connect_fleet",
    "deserialize_handoff",
    "maybe_promote",
    "read_checkpoint",
    "read_lease",
    "run_worker",
    "serialize_handoff",
    "write_checkpoint",
    "write_lease",
]

#: control-plane RPC timeout for NON-streaming calls (probe/stats/scale);
#: loopback and intra-fleet DCN both answer in milliseconds, so a second of
#: silence means the worker is gone, not slow
CONTROL_TIMEOUT_S = 30.0

#: per-read ceiling on a token stream: long enough for any cold compile a
#: first token can hide behind, short enough that a genuinely wedged worker
#: is eventually declared dead instead of pinning the relay forever
STREAM_READ_TIMEOUT_S = 600.0

#: errors that mean "the worker is unreachable" — the caller suspects the
#: host and routes around it (the reconciliation loop owns re-probing)
_DEAD_ERRORS = (ConnectionError, OSError, TimeoutError)

#: host lifecycle states (docs/serving.md "Fault tolerance"): only a live
#: host takes traffic; suspect/dead are routed around and re-probed; a
#: probation host is being readmitted but not yet trusted
HOST_LIVE = "live"
HOST_SUSPECT = "suspect"
HOST_DEAD = "dead"
HOST_PROBATION = "probation"

#: bounded decorrelated-jitter retry envelope for IDEMPOTENT control RPCs
#: (ping/probe/stats/health): one slow scrape must cost a retry, not a host
RETRY_ATTEMPTS = 2
RETRY_BASE_S = 0.05
RETRY_CAP_S = 0.5

#: rendezvous-dir control files: the fenced coordinator checkpoint and the
#: heartbeat lease (both written under atomic rename)
CHECKPOINT_FILE = "coordinator.json"
LEASE_FILE = "coordinator.lease"


class HostDied(RuntimeError):
    """A remote host failed mid-stream (transport death or injected fault).
    Raised by :class:`_RemoteStream`; the coordinator's stream guard turns it
    into a sibling retry (zero tokens emitted) or a clean
    :class:`StreamInterrupted` (tokens already emitted)."""


class StreamInterrupted(RuntimeError):
    """A stream that had already emitted tokens lost its host: the clean
    503-shaped error record — the consumer learns the stream is over *now*,
    instead of hanging on a dead socket. ``emitted`` carries how many tokens
    arrived before the cut."""

    status = 503

    def __init__(self, detail: str, *, emitted: int = 0):
        super().__init__(detail)
        self.detail = detail
        self.emitted = int(emitted)


# ------------------------------------------------------------ checkpoint & lease


def _read_json_file(path: Path) -> "Optional[Dict[str, Any]]":
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def read_checkpoint(fleet_dir: "str | Path") -> "Optional[Dict[str, Any]]":
    """The coordinator's persisted checkpoint (fleet spec, roster, monotonic
    epoch), or None when the rendezvous dir holds none / a torn write."""
    return _read_json_file(Path(fleet_dir).expanduser() / CHECKPOINT_FILE)


def write_checkpoint(
    fleet_dir: "str | Path",
    *,
    epoch: int,
    num_hosts: int,
    roster: "List[Dict[str, Any]]",
    failovers: int = 0,
    announce_floor: int = 0,
) -> bool:
    """Persist the coordinator checkpoint under atomic rename, FENCED on the
    epoch: when the directory already holds a higher epoch a newer
    coordinator exists and this writer is the zombie — the write is refused
    (returns False) instead of clobbering the living fleet's metadata."""
    root = Path(fleet_dir).expanduser()
    root.mkdir(parents=True, exist_ok=True)
    current = read_checkpoint(root)
    if current is not None and int(current.get("epoch", 0)) > int(epoch):
        return False
    payload = {
        "version": 1,
        "epoch": int(epoch),
        "num_hosts": int(num_hosts),
        "roster": roster,
        "failovers": int(failovers),
        #: the announce-epoch floor THIS fleet generation accepted: a
        #: same-generation successor (maybe_promote) must keep accepting the
        #: generation's original announces, while a fresh connect in the same
        #: dir raises the floor to this checkpoint's epoch
        "announce_floor": int(announce_floor),
        "written_at": time.time(),  # wall clock: read by OTHER processes
    }
    tmp = root / (CHECKPOINT_FILE + ".tmp")
    tmp.write_text(json.dumps(payload, sort_keys=True))
    os.replace(tmp, root / CHECKPOINT_FILE)
    return True


def read_lease(fleet_dir: "str | Path") -> "Optional[Dict[str, Any]]":
    return _read_json_file(Path(fleet_dir).expanduser() / LEASE_FILE)


def write_lease(
    fleet_dir: "str | Path", *, epoch: int, owner: int, ttl_s: float
) -> bool:
    """Heartbeat the coordinator lease (atomic rename, epoch-fenced like
    :func:`write_checkpoint`): workers watch its expiry to detect a dead
    coordinator, and a zombie's heartbeat is refused the moment a
    higher-epoch successor exists."""
    root = Path(fleet_dir).expanduser()
    root.mkdir(parents=True, exist_ok=True)
    current = read_lease(root)
    if current is not None and int(current.get("epoch", 0)) > int(epoch):
        return False
    payload = {
        "epoch": int(epoch),
        "owner": int(owner),
        "ttl_s": float(ttl_s),
        "expires_at": time.time() + float(ttl_s),  # wall clock: crosses processes
    }
    tmp = root / (LEASE_FILE + ".tmp")
    tmp.write_text(json.dumps(payload, sort_keys=True))
    os.replace(tmp, root / LEASE_FILE)
    return True


def lease_expired(lease: "Optional[Dict[str, Any]]", *, grace_s: float = 0.0) -> bool:
    """Whether a lease is missing or past its expiry (wall clock — the one
    cross-process time base; a fresh write always postdates a dead one)."""
    if lease is None:
        return True
    try:
        return time.time() > float(lease.get("expires_at", 0.0)) + float(grace_s)
    except (TypeError, ValueError):
        return True


# ---------------------------------------------------------------------- handoff wire


def serialize_handoff(payload: Dict[str, Any]) -> bytes:
    """Encode a handoff payload (``_export_admission``'s dict) for the wire:
    KV pages/rows as an uncompressed ``npz``, the scalar metadata as JSON
    riding inside it. The ``trace`` never crosses (request timelines are
    per-process); the absolute-monotonic ``deadline``/``created_at`` are
    rebased to RELATIVE seconds so the importing host's clock domain applies
    them correctly."""
    meta = {
        "prompt": [int(t) for t in payload["prompt"]],
        "first": int(payload["first"]),
        "lengths": int(payload["lengths"]),
        "max_new": int(payload["max_new"]),
        "produced": int(payload["produced"]),
        "echo": [int(t) for t in payload.get("echo", [])],
        "grammar": int(payload.get("grammar", 0)),
        "priority": int(payload.get("priority", 1)),
        "tenant": payload.get("tenant"),
        "deadline_remaining_s": remaining_s(payload.get("deadline")),
        "age_s": time.monotonic() - payload.get("created_at", time.monotonic()),
        "block_size": payload.get("block_size"),
    }
    arrays: Dict[str, np.ndarray] = {}
    if payload.get("pages") is not None:
        meta["kind"] = "pages"
        for i, layer in enumerate(payload["pages"]):
            for name, buf in layer.items():
                arrays[f"p{i}.{name}"] = np.asarray(buf)
        meta["layers"] = len(payload["pages"])
    else:
        meta["kind"] = "row"
        for i, layer in enumerate(payload["row"]):
            for name, buf in layer.items():
                arrays[f"p{i}.{name}"] = np.asarray(buf)
        meta["layers"] = len(payload["row"])
    arrays["__meta__"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    out = io.BytesIO()
    np.savez(out, **arrays)
    return out.getvalue()


def deserialize_handoff(data: bytes) -> Dict[str, Any]:
    """Decode :func:`serialize_handoff`'s bytes back into the payload dict
    :meth:`ContinuousBatcher.import_handoff` consumes (pages as numpy — the
    importing engine places them onto its own submesh)."""
    with np.load(io.BytesIO(data)) as bundle:
        meta = json.loads(bytes(bundle["__meta__"]).decode())
        layers = [
            {
                key.split(".", 1)[1]: bundle[key]
                for key in bundle.files
                if key.startswith(f"p{i}.")
            }
            for i in range(meta["layers"])
        ]
    remaining = meta.pop("deadline_remaining_s")
    age = meta.pop("age_s")
    kind = meta.pop("kind")
    meta.pop("layers")
    payload: Dict[str, Any] = dict(meta)
    payload["pages" if kind == "pages" else "row"] = tuple(layers)
    payload["deadline"] = None if remaining is None else time.monotonic() + remaining
    payload["created_at"] = time.monotonic() - max(age, 0.0)
    payload["trace"] = None
    return payload


# --------------------------------------------------------------------- worker agent


class _ControlHandler(BaseHTTPRequestHandler):
    """Route table of one worker's control server. HTTP/1.0 close-delimited
    responses keep the streaming path trivial (the coordinator reads lines
    until EOF); every request is its own connection — loopback/DCN accepts
    are microseconds against a decode chunk."""

    agent: "WorkerAgent"  # set by WorkerAgent on the subclass

    def log_message(self, fmt: str, *args: Any) -> None:  # route to our logger
        logger.debug(f"cluster control: {fmt % args}")

    def _json(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _drop_connection(self) -> None:
        """Simulate a dead worker for an injected fault: sever the TCP
        connection without any response bytes — the coordinator sees exactly
        what a SIGKILLed process produces."""
        self.close_connection = True
        try:
            self.connection.close()
        except OSError:  # pragma: no cover - defensive
            pass

    def _fault_gate(self) -> bool:
        """Consult the worker-side fault plan before dispatching; True when
        the request was injected away (connection already dropped)."""
        faults = self.agent.faults
        if faults is None:
            return False
        try:
            faults.check_rpc(self.agent.process_id, self.path)
        except ConnectionError:
            self._drop_connection()
            return True
        return False

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        agent = self.agent
        if self._fault_gate():
            return
        try:
            if self.path == "/ctrl/ping":
                self._json(200, {"ok": True, "process_id": agent.process_id, "role": agent.role})
            elif self.path == "/ctrl/stats":
                self._json(200, {"stats": _jsonable(agent.engine.stats())})
            elif self.path == "/ctrl/health":
                self._json(200, _jsonable(agent.engine.health()))
            else:
                self._json(404, {"detail": f"no control route for {self.path}"})
        except Exception as exc:  # pragma: no cover - defensive
            logger.exception("control GET failed")
            self._json(500, {"detail": f"{type(exc).__name__}: {exc}"})

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        agent = self.agent
        if self._fault_gate():
            return
        try:
            if self.path == "/ctrl/submit":
                self._submit(json.loads(self._body() or b"{}"))
            elif self.path == "/ctrl/import":
                self._import(self._body())
            elif self.path == "/ctrl/probe":
                request = json.loads(self._body() or b"{}")
                self._json(200, agent.probe(request.get("prompt")))
            elif self.path == "/ctrl/scale":
                request = json.loads(self._body() or b"{}")
                count = agent.engine.scale_to(
                    int(request["replicas"]), role=request.get("role")
                )
                self._json(200, {"replicas": count})
            elif self.path == "/ctrl/warmup":
                agent.engine.warmup()
                self._json(200, {"ok": True})
            elif self.path == "/ctrl/drain":
                agent.engine.close(wait=True)
                self._json(200, {"ok": True})
            elif self.path == "/ctrl/shutdown":
                self._json(200, {"ok": True})
                agent.request_shutdown()
            else:
                self._json(404, {"detail": f"no control route for {self.path}"})
        except (QueueFullError, DeadlineExceeded) as exc:
            self._shed(exc)
        except Exception as exc:
            logger.exception("control POST failed")
            try:
                self._json(500, {"detail": f"{type(exc).__name__}: {exc}"})
            except _DEAD_ERRORS:
                pass

    # ------------------------------------------------------------ streaming routes

    def _shed(self, exc: BaseException) -> None:
        """Map the engine's shed exceptions onto the wire so the coordinator
        re-raises the SAME types (429 queue/tenant, 503 deadline) — the
        fleet-wide overload posture survives the process boundary."""
        if isinstance(exc, TenantThrottled):
            self._json(429, {
                "detail": exc.detail, "kind": "tenant_limit",
                "retry_after": exc.retry_after_s, "tenant": exc.tenant,
            })
        elif isinstance(exc, QueueFullError):
            self._json(429, {
                "detail": exc.detail, "kind": "queue_full", "retry_after": exc.retry_after_s,
            })
        else:
            self._json(503, {"detail": str(exc) or "deadline exceeded", "kind": "deadline"})

    def _stream(self, stream: Any, *, export: bool) -> None:
        """Relay an engine token stream as ndjson lines, flushed per chunk so
        the coordinator's client sees each token as it is produced. A broken
        pipe (coordinator/client went away) closes the engine stream so the
        producer never decodes to a dead connection. An EXPORT stream's
        handoff payload rides as a final base64 ``npz`` line."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        faults = self.agent.faults
        cut_after = (
            faults.stream_cut_after(self.agent.process_id) if faults is not None else None
        )
        sent = 0
        try:
            for chunk in stream:
                if cut_after is not None and sent >= cut_after:
                    # injected stream_cut: sever mid-stream with no end marker
                    # — the coordinator sees a truncated stream, exactly as if
                    # the worker died between flushes
                    _close_quietly(stream)
                    self._drop_connection()
                    return
                tokens = [int(t) for t in np.asarray(chunk).ravel()]
                self.wfile.write(json.dumps({"t": tokens}).encode() + b"\n")
                self.wfile.flush()
                sent += 1
            if export and getattr(stream, "handoff", None) is not None:
                blob = base64.b64encode(serialize_handoff(stream.handoff)).decode()
                self.wfile.write(json.dumps({"handoff": blob}).encode() + b"\n")
            self.wfile.write(b'{"end": true}\n')
            self.wfile.flush()
        except _DEAD_ERRORS:
            _close_quietly(stream)
        except Exception as exc:
            _close_quietly(stream)
            try:
                self.wfile.write(
                    json.dumps({"error": f"{type(exc).__name__}: {exc}"}).encode() + b"\n"
                )
            except _DEAD_ERRORS:
                pass

    def _submit(self, request: Dict[str, Any]) -> None:
        agent = self.agent
        deadline = request.get("deadline_remaining_s")
        kwargs: Dict[str, Any] = {
            "max_new_tokens": request.get("max_new_tokens"),
            "constraint": request.get("constraint"),
            "deadline": None if deadline is None else time.monotonic() + float(deadline),
            "tenant": request.get("tenant"),
            "priority": request.get("priority"),
        }
        export = bool(request.get("export"))
        if export:
            kwargs["export_handoff"] = True
        stream = agent.engine.submit([int(t) for t in request["prompt"]], **kwargs)
        self._stream(stream, export=export)

    def _import(self, body: bytes) -> None:
        stream = self.agent.engine.import_handoff(deserialize_handoff(body))
        self._stream(stream, export=False)


def _close_quietly(stream: Any) -> None:
    closer = getattr(stream, "close", None)
    if callable(closer):
        try:
            closer()
        except Exception:  # pragma: no cover - defensive
            pass


def _jsonable(obj: Any) -> Any:
    """Strip a stats/health dict down to JSON-encodable leaves (numpy scalars
    become Python numbers; anything else stringifies)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def _current_trace() -> Any:
    """The active request trace, if tracing is on (lazy import: cluster must
    stay importable without the observability stack initialized)."""
    from unionml_tpu.observability.trace import current_trace

    return current_trace()


def _host_state(host: Any) -> str:
    """A handle's lifecycle state, with the boolean-only (duck-typed) handle
    fallback — uniform rows for /healthz, /debug/fleet, and /metrics."""
    state = getattr(host, "state", None)
    if isinstance(state, str):
        return state
    return HOST_LIVE if getattr(host, "alive", True) else HOST_DEAD


def _host_transition_s(host: Any) -> float:
    fn = getattr(host, "last_transition_s", None)
    return round(float(fn()), 3) if callable(fn) else 0.0


def _fleet_probe(engine: Any, prompt: Optional[Sequence[int]]) -> Dict[str, Any]:
    """One host's routing signals in a single fetch: token-weighted load,
    the radix probe for this prompt (the fleet-global prefix tier), the SLO
    breach flag, and the live replica count."""
    cached = 0
    if prompt is not None:
        probe = getattr(engine, "cached_prefix_tokens", None)
        if callable(probe):
            cached = int(probe([int(t) for t in prompt]))
    health_fn = getattr(engine, "health", None)
    breaching = False
    if callable(health_fn):
        breaching = health_fn().get("state") == "breach"
    replicas = getattr(engine, "replicas", 1)
    return {
        "load": float(engine.load()),
        "cached": cached,
        "breaching": bool(breaching),
        "replicas": int(replicas) if isinstance(replicas, (int, np.integer)) else 1,
    }


class WorkerAgent:
    """One worker process's control server around its local engine.

    Binds a loopback (or fleet-network) :class:`ThreadingHTTPServer` on an
    OS-assigned port, serves the control routes (`/ctrl/submit`,
    ``/ctrl/import``, ``/ctrl/probe``, ``/ctrl/stats``, ``/ctrl/health``,
    ``/ctrl/scale``, ``/ctrl/warmup``, ``/ctrl/drain``, ``/ctrl/shutdown``)
    on daemon threads, and announces itself into the fleet rendezvous
    directory so the coordinator can connect."""

    def __init__(
        self,
        engine: Any,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        process_id: Optional[int] = None,
        role: str = "mixed",
        fault_plan: "FaultPlan | ArmedFaultPlan | None" = None,
    ):
        from unionml_tpu import distributed

        self.engine = engine
        self.role = role
        self.process_id = distributed.process_index() if process_id is None else int(process_id)
        handler = type("_BoundControlHandler", (_ControlHandler,), {"agent": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None
        #: set by /ctrl/shutdown (and close()) — run_worker's exit signal
        self.shutdown_event = threading.Event()
        #: this worker's rendezvous file, tracked so graceful shutdown can
        #: remove it (a stale announce would point a restarted fleet in the
        #: same --fleet-dir at a dead address)
        self._announce_path: Optional[Path] = None
        if fault_plan is None:
            fault_plan = FaultPlan.from_env()
        #: worker-side fault injector (serving/faults.py); None = no plan
        self.faults: Optional[ArmedFaultPlan] = (
            fault_plan.arm() if isinstance(fault_plan, FaultPlan) else fault_plan
        )

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def probe(self, prompt: Optional[Sequence[int]]) -> Dict[str, Any]:
        return _fleet_probe(self.engine, prompt)

    def start(self) -> "WorkerAgent":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
                daemon=True, name=f"unionml-tpu-worker-{self.process_id}",
            )
            self._thread.start()
            logger.info(f"worker {self.process_id} control server on {self.address} (role={self.role})")
        return self

    def announce(self, fleet_dir: "str | Path") -> Path:
        """Write this worker's rendezvous file (atomic: the coordinator must
        never read a half-written announcement). The announce is EPOCH-STAMPED
        with the fleet checkpoint's current epoch (0 before any coordinator
        wrote one): the reconciliation loop and ``connect_fleet`` reject
        announces from a previous fleet generation, so a stale file can never
        point a fresh fleet at a dead address."""
        root = Path(fleet_dir).expanduser()
        root.mkdir(parents=True, exist_ok=True)
        checkpoint = read_checkpoint(root)
        path = root / f"host-{self.process_id}.json"
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps({
            "process_id": self.process_id,
            "host": self.host,
            "port": self.port,
            "pid": os.getpid(),
            "role": self.role,
            "epoch": int(checkpoint.get("epoch", 0)) if checkpoint else 0,
        }))
        os.replace(tmp, path)
        self._announce_path = path
        return path

    def request_shutdown(self) -> None:
        self.shutdown_event.set()

    def close(self, *, close_engine: bool = True) -> None:
        self.shutdown_event.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._announce_path is not None:
            # rendezvous hygiene: a gracefully-stopped worker withdraws its
            # announce so a restarted fleet in the same dir never pings it
            try:
                self._announce_path.unlink()
            except OSError:  # pragma: no cover - already gone / dir removed
                pass
            self._announce_path = None
        if close_engine:
            self.engine.close(wait=True)


# ---------------------------------------------------------------------- host handles


class LocalHost:
    """The coordinator's handle on an engine living in ITS OWN process (host 0
    usually serves too) — direct calls, no HTTP hop."""

    def __init__(self, engine: Any, *, host_id: int = 0, role: str = "mixed"):
        self.engine = engine
        self.host_id = int(host_id)
        self.role = role
        self.alive = True
        self.address = "local"
        #: an in-process engine has no transport to fail: its lifecycle is
        #: degenerate (live while ``alive``); counters exist so the fleet
        #: aggregation reads every host uniformly
        self.suspects = 0
        self.rejoins = 0
        self.rpc_retries = 0
        self.epoch = 0

    @property
    def state(self) -> str:
        return HOST_LIVE if self.alive else HOST_DEAD

    def last_transition_s(self) -> float:
        return 0.0

    @property
    def gen(self) -> Any:
        """The underlying Generator (engine or first replica) — the
        ``/v1/*`` routes resolve generation config through ``batchers[0]``,
        and on a multi-host fleet ``batchers`` are HOST handles; without this
        delegation every OpenAI completion against a coordinator-fronted
        fleet answered 500."""
        gen = getattr(self.engine, "gen", None)
        if gen is None:
            batchers = getattr(self.engine, "batchers", None)
            if batchers:
                gen = getattr(batchers[0], "gen", None)
        return gen

    def probe(self, prompt: Optional[Sequence[int]]) -> Dict[str, Any]:
        return _fleet_probe(self.engine, prompt)

    def submit(self, prompt: Sequence[int], *, export: bool = False, **kwargs: Any) -> Any:
        if export:
            kwargs["export_handoff"] = True
        return self.engine.submit(prompt, **kwargs)

    def import_handoff(self, payload: Any) -> Any:
        if isinstance(payload, (bytes, bytearray)):
            payload = deserialize_handoff(bytes(payload))
        return self.engine.import_handoff(payload)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def health(self) -> Dict[str, Any]:
        fn = getattr(self.engine, "health", None)
        if callable(fn):
            payload = dict(fn())  # copy: the engine may serve a TTL-cached dict
        else:
            payload = {"score": 1.0, "state": "ok", "state_code": 0, "enabled": False}
        payload["host_state"] = self.state
        payload["last_transition_s"] = 0.0
        return payload

    def occupancy(self) -> "Tuple[int, int]":
        fn = getattr(self.engine, "occupancy", None)
        if callable(fn):
            return fn()
        resident = sum(b.occupancy()[0] for b in getattr(self.engine, "batchers", ()))
        waiting = sum(b.occupancy()[1] for b in getattr(self.engine, "batchers", ()))
        return resident, waiting

    def warmup(self) -> None:
        self.engine.warmup()

    def scale_to(self, n: int, *, role: Optional[str] = None) -> int:
        return self.engine.scale_to(n, role=role)

    def replicas(self) -> int:
        return int(getattr(self.engine, "replicas", 1) or 1)

    def close(self, *, shutdown_worker: bool = False) -> None:
        self.engine.close(wait=True)


class _RemoteStream:
    """Iterator over a worker's ndjson token stream. ``close()`` drops the
    TCP connection, which the worker maps to closing the engine stream — the
    relay's client-disconnect contract crosses the process boundary. An
    EXPORT stream's serialized handoff lands on ``.handoff`` after the last
    token."""

    def __init__(
        self,
        conn: HTTPConnection,
        response: Any,
        host: "RemoteHost",
        *,
        cut_after: Optional[int] = None,
    ):
        self._conn = conn
        self._response = response
        self._host = host
        self._closed = False
        self._yielded = 0
        #: coordinator-side injected stream_cut: sever after this many chunks
        self._cut_after = cut_after
        self.handoff: Optional[bytes] = None

    def __iter__(self) -> "Iterator[np.ndarray]":
        return self

    def __next__(self) -> np.ndarray:
        while True:
            if self._cut_after is not None and self._yielded >= self._cut_after:
                self.close()
                self._host.mark_suspect(ConnectionError("fault-injected stream_cut"))
                raise HostDied(
                    f"worker {self._host.host_id} stream cut after {self._yielded} chunks "
                    "(fault-injected)"
                )
            try:
                line = self._response.readline()
            except _DEAD_ERRORS as exc:
                self._host.mark_suspect(exc)
                self.close()
                raise HostDied(f"worker {self._host.host_id} died mid-stream: {exc}") from exc
            if not line:
                # connection closed without an end marker: the worker died
                self.close()
                if not self._closed_cleanly:
                    self._host.mark_suspect(ConnectionError("stream truncated"))
                    raise HostDied(f"worker {self._host.host_id} truncated the stream")
                raise StopIteration
            record = json.loads(line)
            if "t" in record:
                self._yielded += 1
                return np.asarray(record["t"], np.int32)
            if "handoff" in record:
                self.handoff = base64.b64decode(record["handoff"])
                continue
            if record.get("end"):
                self._closed_cleanly = True
                self.close()
                raise StopIteration
            if "error" in record:
                self.close()
                raise RuntimeError(f"worker {self._host.host_id} stream failed: {record['error']}")

    _closed_cleanly = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._conn.close()
            except Exception:  # pragma: no cover - defensive
                pass


class RemoteHost:
    """The coordinator's handle on a worker process, over the HTTP control
    plane — with a lifecycle, not a boolean: ``live → suspect`` on a
    transport failure (routed around, re-probed by the reconciliation loop),
    ``suspect → dead`` after consecutive probe failures, ``→ probation`` on a
    successful re-probe or a fresh epoch-stamped announce, and
    ``probation → live`` after the configured probe streak plus a warmup.
    Idempotent control RPCs (ping/probe/stats/health) retry with bounded
    decorrelated jitter before suspecting the host; non-idempotent calls
    (submit/import/scale) never retry in-band — a wedged worker retried into
    is a wedged fleet."""

    def __init__(
        self,
        address: str,
        *,
        host_id: int,
        role: str = "mixed",
        epoch: int = 0,
        faults: "Optional[ArmedFaultPlan]" = None,
    ):
        self.address = address
        self.host_id = int(host_id)
        self.role = role
        host, _, port = address.partition(":")
        self._host, self._port = host, int(port)
        #: announce epoch this handle was bound from (stale-announce fencing)
        self.epoch = int(epoch)
        #: coordinator-side fault injector (serving/faults.py); None = no plan
        self.faults = faults
        #: lifecycle telemetry (summed into stats()["fleet"])
        self.suspects = 0
        self.rejoins = 0
        self.rpc_retries = 0
        self._slock = threading.Lock()
        self._state = HOST_LIVE
        self._state_since = time.monotonic()
        self._consecutive_failures = 0
        self._probation_successes = 0
        self._down_since: Optional[float] = None
        self._retry_rng = random.Random(host_id)
        #: (address, epoch, pid) of the announce this handle was bound from —
        #: the reconciler's dedup key for rebinding returning workers
        self._bound_announce: "Optional[Tuple[str, int, Any]]" = None

    # ------------------------------------------------------------- lifecycle

    @property
    def alive(self) -> bool:
        """Only a LIVE host takes traffic; suspect/dead/probation are all
        routed around (the scheduler's view is binary, the reconciler's is
        not)."""
        return self._state == HOST_LIVE

    @property
    def state(self) -> str:
        return self._state

    def last_transition_s(self) -> float:
        return max(time.monotonic() - self._state_since, 0.0)

    def _transition_locked(self, state: str) -> bool:
        # caller holds self._slock (the *_locked convention)
        if state == self._state:
            return False
        self._state = state
        self._state_since = time.monotonic()
        return True

    def mark_suspect(self, exc: BaseException) -> bool:
        """A transport failure: live → suspect (dead stays dead — only the
        reconciler readmits). Returns True on an actual live→suspect edge."""
        with self._slock:
            if self._state == HOST_DEAD:
                return False
            was_live = self._state == HOST_LIVE
            changed = self._transition_locked(HOST_SUSPECT)
            if changed and was_live:
                self.suspects += 1
                if self._down_since is None:
                    self._down_since = time.monotonic()
            self._probation_successes = 0
        if changed and was_live:
            logger.warning(
                f"fleet host {self.host_id} ({self.address}) suspect: {exc} "
                "(routed around; reconciliation will re-probe)"
            )
        return changed and was_live

    def mark_dead(self, exc: "Optional[BaseException]" = None) -> None:
        """The terminal demotion (N consecutive probe failures, or an
        explicit operator action); only a fresh announce or a successful
        re-probe brings the host back through probation."""
        with self._slock:
            if self._state == HOST_LIVE and self._down_since is None:
                self._down_since = time.monotonic()
                self.suspects += 1
            changed = self._transition_locked(HOST_DEAD)
        if changed:
            logger.warning(
                f"fleet host {self.host_id} ({self.address}) marked dead"
                + (f": {exc}" if exc is not None else "")
            )

    def note_probe_success(self, probation_probes: int) -> bool:
        """A reconciliation probe answered: suspect/dead → probation, and
        each further success extends the streak. True when the streak has
        reached ``probation_probes`` (the host is ready to go live)."""
        with self._slock:
            if self._state in (HOST_SUSPECT, HOST_DEAD):
                self._transition_locked(HOST_PROBATION)
                self._probation_successes = 1
            elif self._state == HOST_PROBATION:
                self._probation_successes += 1
            self._consecutive_failures = 0
            return (
                self._state == HOST_PROBATION
                and self._probation_successes >= int(probation_probes)
            )

    def note_probe_failure(self, dead_after: int) -> None:
        """A reconciliation probe failed: probation collapses back to
        suspect, and ``dead_after`` consecutive failures demote to dead."""
        with self._slock:
            self._consecutive_failures += 1
            if self._state == HOST_PROBATION:
                self._transition_locked(HOST_SUSPECT)
                self._probation_successes = 0
            demote = (
                self._state == HOST_SUSPECT
                and self._consecutive_failures >= int(dead_after)
            )
        if demote:
            self.mark_dead(ConnectionError(f"{dead_after} consecutive probe failures"))

    def go_live(self) -> "Tuple[bool, Optional[float]]":
        """Probation passed (probes + warmup): take traffic again. Returns
        ``(transitioned, down_since)`` so the coordinator can observe the
        outage-to-recovery latency."""
        with self._slock:
            changed = self._transition_locked(HOST_LIVE)
            down = self._down_since
            self._down_since = None
            self._consecutive_failures = 0
            self._probation_successes = 0
            if changed:
                self.rejoins += 1
        if changed:
            logger.info(f"fleet host {self.host_id} ({self.address}) rejoined (live)")
        return changed, down

    def rebind(self, address: str, *, epoch: int, role: "Optional[str]" = None) -> None:
        """Bind this handle to a returning worker's fresh announce (possibly
        a new address — a restarted or replacement process) and enter
        probation; traffic waits for the probe streak + warmup."""
        with self._slock:
            self.address = address
            host, _, port = address.partition(":")
            self._host, self._port = host, int(port)
            self.epoch = int(epoch)
            if role is not None:
                self.role = role
            self._transition_locked(HOST_PROBATION)
            self._probation_successes = 0
            self._consecutive_failures = 0
            if self._down_since is None:
                self._down_since = time.monotonic()
        logger.info(
            f"fleet host {self.host_id} re-announced at {address} (epoch {epoch}); probation"
        )

    # ------------------------------------------------------------- transport

    def _connect(self, timeout: Optional[float]) -> HTTPConnection:
        return HTTPConnection(self._host, self._port, timeout=timeout)

    def _call(self, method: str, path: str, body: Optional[bytes] = None,
              *, timeout: float = CONTROL_TIMEOUT_S, mark: bool = True) -> Dict[str, Any]:
        """One non-streaming control RPC; a transport error suspects the host
        (``mark=False`` lets the retry wrapper defer the verdict) and
        re-raises. NEVER call while holding a lock (TPU013): a stalled
        worker must cost this call, not the whole coordinator."""
        if self.faults is not None:
            self.faults.check_rpc(self.host_id, path)
        conn = self._connect(timeout)
        try:
            conn.request(method, path, body=body, headers={"Content-Type": "application/json"})
            response = conn.getresponse()
            payload = json.loads(response.read() or b"{}")
            if response.status >= 400:
                _raise_shed(response.status, payload)
            return payload
        except _DEAD_ERRORS as exc:
            if mark:
                self.mark_suspect(exc)
            raise
        finally:
            conn.close()

    def _call_retry(self, method: str, path: str, body: Optional[bytes] = None,
                    *, timeout: float = CONTROL_TIMEOUT_S,
                    attempts: int = RETRY_ATTEMPTS) -> Dict[str, Any]:
        """Bounded decorrelated-jitter retry for IDEMPOTENT control RPCs
        (ping/probe/stats/health — tpu-lint TPU015's good idiom): a transient
        drop or slow scrape costs a retry, not a host; only the exhausted
        envelope suspects. Non-idempotent calls must use :meth:`_call`."""
        sleep_s = RETRY_BASE_S
        last: Optional[BaseException] = None
        for attempt in range(max(int(attempts), 1)):
            try:
                return self._call(method, path, body, timeout=timeout, mark=False)
            except (QueueFullError, DeadlineExceeded):
                raise  # a shed is an ANSWER, not a transport failure
            except _DEAD_ERRORS as exc:
                last = exc
                if attempt + 1 >= max(int(attempts), 1):
                    break
                with self._slock:
                    self.rpc_retries += 1
                sleep_s = min(RETRY_CAP_S, self._retry_rng.uniform(RETRY_BASE_S, sleep_s * 3))
                time.sleep(sleep_s)
        assert last is not None
        self.mark_suspect(last)
        raise last

    def _stream_call(self, path: str, body: bytes, content_type: str) -> _RemoteStream:
        if self.faults is not None:
            self.faults.check_rpc(self.host_id, path)
            cut_after = self.faults.stream_cut_after(self.host_id)
        else:
            cut_after = None
        conn = self._connect(CONTROL_TIMEOUT_S)
        try:
            try:
                # connect under the control timeout, then RELAX the socket for
                # the stream's lifetime BEFORE the request: a cold first token
                # can sit behind a multi-minute XLA compile, and for
                # close-delimited responses http.client drops conn.sock at
                # getresponse() — there is no socket left to retune afterwards
                # (a 30 s-stalled stream used to mis-classify the worker as
                # dead here)
                conn.connect()
                if conn.sock is not None:
                    conn.sock.settimeout(STREAM_READ_TIMEOUT_S)
                conn.request("POST", path, body=body, headers={"Content-Type": content_type})
                response = conn.getresponse()
            except _DEAD_ERRORS as exc:
                self.mark_suspect(exc)
                raise
            if response.status >= 400:
                # a garbage error body (truncated read, non-JSON payload)
                # raises out of here too — the outer close still runs
                payload = json.loads(response.read() or b"{}")
                _raise_shed(response.status, payload)
        except BaseException:
            # every failure path releases the socket: errors not in
            # _DEAD_ERRORS (interrupts, JSON decode failures on the shed
            # payload) used to leak the connection
            conn.close()
            raise
        return _RemoteStream(conn, response, self, cut_after=cut_after)

    def ping(self, timeout: float = CONTROL_TIMEOUT_S) -> Dict[str, Any]:
        return self._call_retry("GET", "/ctrl/ping", timeout=timeout)

    def probe(self, prompt: Optional[Sequence[int]]) -> Dict[str, Any]:
        body = json.dumps({"prompt": [int(t) for t in prompt] if prompt is not None else None})
        return self._call_retry("POST", "/ctrl/probe", body.encode())

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        constraint: Optional[int] = None,
        deadline: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        export: bool = False,
    ) -> _RemoteStream:
        body = json.dumps({
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": max_new_tokens,
            "constraint": constraint,
            "deadline_remaining_s": remaining_s(deadline),
            "tenant": tenant,
            "priority": priority,
            "export": export,
        }).encode()
        return self._stream_call("/ctrl/submit", body, "application/json")

    def import_handoff(self, payload: Any) -> _RemoteStream:
        if not isinstance(payload, (bytes, bytearray)):
            payload = serialize_handoff(payload)
        return self._stream_call("/ctrl/import", bytes(payload), "application/octet-stream")

    def stats(self) -> Dict[str, Any]:
        return self._call_retry("GET", "/ctrl/stats")["stats"]

    def health(self) -> Dict[str, Any]:
        if not self.alive:
            return {
                "score": 0.0, "state": "breach", "state_code": 2, "enabled": True,
                "dead": True, "host_state": self._state,
                "last_transition_s": round(self.last_transition_s(), 3),
            }
        try:
            payload = self._call_retry("GET", "/ctrl/health")
        except _DEAD_ERRORS:
            return {
                "score": 0.0, "state": "breach", "state_code": 2, "enabled": True,
                "dead": True, "host_state": self._state,
                "last_transition_s": round(self.last_transition_s(), 3),
            }
        payload["host_state"] = self._state
        payload["last_transition_s"] = round(self.last_transition_s(), 3)
        return payload

    def occupancy(self) -> "Tuple[int, int]":
        stats = self.stats()
        return int(stats.get("resident") or 0), int(stats.get("waiting") or 0)

    def warmup(self) -> None:
        self._call("POST", "/ctrl/warmup", b"{}", timeout=600.0)

    def scale_to(self, n: int, *, role: Optional[str] = None) -> int:
        payload = self._call(
            "POST", "/ctrl/scale", json.dumps({"replicas": int(n), "role": role}).encode(),
            timeout=600.0,
        )
        return int(payload["replicas"])

    def replicas(self) -> int:
        try:
            return int(self.stats().get("replicas") or 1)
        except _DEAD_ERRORS:
            return 0

    def close(self, *, shutdown_worker: bool = False) -> None:
        if not self.alive:
            return
        try:
            self._call("POST", "/ctrl/drain", b"{}", timeout=600.0)
            if shutdown_worker:
                self._call("POST", "/ctrl/shutdown", b"{}")
        except _DEAD_ERRORS:
            pass


def _raise_shed(status: int, payload: Dict[str, Any]) -> None:
    """Re-raise a worker's shed response as the SAME exception type the local
    engine would have raised, Retry-After preserved."""
    kind = payload.get("kind")
    detail = payload.get("detail") or f"worker answered {status}"
    if kind == "tenant_limit":
        raise TenantThrottled(
            detail, retry_after_s=float(payload.get("retry_after") or 1.0),
            tenant=payload.get("tenant"),
        )
    if kind == "queue_full":
        raise QueueFullError(detail, retry_after_s=float(payload.get("retry_after") or 1.0))
    if kind == "deadline":
        raise DeadlineExceeded(detail)
    raise RuntimeError(f"control call failed ({status}): {detail}")


# --------------------------------------------------------------------- coordinator


class FleetCoordinator:
    """Host-0's routing/admission/scale brain over N host handles.

    Mirrors the engine surface (``submit`` / ``warmup`` / ``stats`` /
    ``health`` / ``load`` / ``scale_to`` / ``close``), so
    ``model.generation_batcher = coordinator`` gives the serving app a
    multi-host fleet with zero route changes — ``/metrics`` grows per-host
    sections, ``/healthz`` per-host scores, ``/debug/fleet`` the host census.

    Routing is the :class:`ReplicaScheduler` at host granularity: per
    submission every live host is probed (one concurrent control RPC each)
    for its token-weighted load, SLO breach flag, and its actual
    cached-prefix length for this prompt — the radix prefix tier made
    FLEET-GLOBAL, so turn 2 of a conversation lands on the host whose KV
    pool already holds turn 1. Dead hosts rank last and are skipped; a
    transport failure during routing marks the host dead and the walk
    continues on its siblings (degrade, don't shed).

    With host roles configured (``host_roles=`` or the
    ``UNIONML_TPU_HOST_ROLES`` export), prompts at least
    ``prefill_threshold`` tokens long prefill on a prefill-role host and
    their finished KV pages cross the control plane to a decode host
    (:func:`serialize_handoff`'s block-native wire format) — token-identical
    to a mixed fleet, with the transfer latency on ``stats()``."""

    def __init__(
        self,
        hosts: Sequence[Any],
        *,
        affinity_tokens: int = 0,
        affinity_margin: int = 2,
        prefill_threshold: Optional[int] = None,
        host_roles: Optional[Sequence[str]] = None,
        fleet_dir: "str | Path | None" = None,
        epoch: int = 0,
        probe_interval_s: Optional[float] = None,
        probation_probes: Optional[int] = None,
        dead_after: Optional[int] = None,
        lease_ttl_s: Optional[float] = None,
        fault_plan: "FaultPlan | ArmedFaultPlan | None" = None,
    ):
        if not hosts:
            raise ValueError("a fleet needs at least one host")
        self.hosts: "List[Any]" = list(hosts)
        if host_roles is not None:
            if len(host_roles) != len(self.hosts):
                raise ValueError(
                    f"host_roles covers {len(host_roles)} hosts but the fleet has {len(self.hosts)}"
                )
            for host, role in zip(self.hosts, host_roles):
                host.role = role
        else:
            env_roles = fleet_host_roles()
            if env_roles:
                expanded: "List[str]" = []
                for role in ("prefill", "decode", "mixed"):
                    expanded.extend([role] * env_roles.get(role, 0))
                if len(expanded) == len(self.hosts) and any(r == "prefill" for r in expanded) and not all(
                    r == "prefill" for r in expanded
                ):
                    for host, role in zip(self.hosts, expanded):
                        host.role = role
                else:
                    logger.warning(
                        f"ignoring UNIONML_TPU_HOST_ROLES={env_roles} over {len(self.hosts)} hosts; "
                        "falling back to a symmetric (all-mixed) host fleet"
                    )
        self._scheduler = ReplicaScheduler(
            len(self.hosts), affinity_tokens=affinity_tokens, affinity_margin=affinity_margin
        )
        if prefill_threshold is None:
            prefill_threshold = serve_prefill_threshold()
        self._prefill_threshold = int(prefill_threshold)
        self._lock = threading.Lock()
        #: fleet-level telemetry (the ReplicaSet counters, one level up)
        self.shed_deadline = 0
        self.shed_queue_full = 0
        self.host_failures = 0
        self.cross_host_handoffs = 0
        self._transfer_ms = LatencyWindow()
        #: fault-tolerance telemetry (stats()["fleet"])
        self.stream_retries = 0
        self.streams_interrupted = 0
        self.coordinator_failovers = 0
        self._recovery_ms = LatencyWindow()
        #: fencing epoch: every checkpoint/lease write carries it, and a
        #: higher epoch on disk means a successor exists — this coordinator
        #: is the zombie and its writes are refused
        self.epoch = int(epoch)
        self.fenced = False
        self.fleet_dir: "Optional[Path]" = (
            Path(fleet_dir).expanduser() if fleet_dir is not None else None
        )
        #: announce-epoch floor: rendezvous files stamped below it belong to
        #: a previous fleet generation and are ignored (hygiene satellite)
        self._announce_floor = 0
        self._probe_interval_s = (
            fleet_probe_interval_s() if probe_interval_s is None else float(probe_interval_s)
        )
        self._probation_probes = (
            fleet_probation_probes() if probation_probes is None else int(probation_probes)
        )
        self._dead_after = fleet_dead_after_probes() if dead_after is None else int(dead_after)
        self._lease_ttl_s = fleet_lease_ttl_s() if lease_ttl_s is None else float(lease_ttl_s)
        self._reconcile_stop = threading.Event()
        self._reconcile_thread: Optional[threading.Thread] = None
        self._faults: Optional[ArmedFaultPlan] = None
        if fault_plan is None:
            fault_plan = FaultPlan.from_env()
        if fault_plan is not None:
            self.arm_faults(fault_plan)

    # -------------------------------------------------------------- fault injection

    def arm_faults(self, plan: "FaultPlan | ArmedFaultPlan") -> ArmedFaultPlan:
        """Arm a deterministic fault plan (serving/faults.py) on this
        coordinator: virtual time starts NOW, and every RemoteHost handle
        consults the shared injector at its transport boundary."""
        armed = plan.arm() if isinstance(plan, FaultPlan) else plan
        self._faults = armed
        for host in self.hosts:
            if isinstance(host, RemoteHost):
                host.faults = armed
        logger.info(
            f"fault plan armed: {len(armed.plan.events)} events over "
            f"{armed.plan.horizon_s:.2f}s (seed {armed.plan.seed})"
        )
        return armed

    # ------------------------------------------------------------------ introspection

    @property
    def batchers(self) -> "Tuple[Any, ...]":
        """The host handles (the ``fleet_health`` duck-typing surface: each
        handle's ``health()`` is one 'replica' row at host granularity)."""
        return tuple(self.hosts)

    @property
    def replicas(self) -> int:
        """Live hosts (the coordinator's fleet-size headline; per-host engine
        replica counts ride ``stats()['hosts']``)."""
        return sum(1 for host in self.hosts if host.alive)

    @property
    def roles(self) -> "List[str]":
        return [host.role for host in self.hosts]

    def _live(self) -> "List[int]":
        return [i for i, host in enumerate(self.hosts) if host.alive]

    def _note_failure(self) -> None:
        with self._lock:
            self.host_failures += 1

    def _probe_all(
        self, indices: "List[int]", prompt: Optional[Sequence[int]]
    ) -> "Dict[int, Dict[str, Any]]":
        """Probe the named hosts concurrently (one control RPC each); a host
        that fails its probe is marked dead and omitted."""
        if len(indices) == 1:
            index = indices[0]
            try:
                return {index: self.hosts[index].probe(prompt)}
            except _DEAD_ERRORS:
                self._note_failure()
                return {}
        from concurrent.futures import ThreadPoolExecutor

        def one(index: int) -> "Tuple[int, Optional[Dict[str, Any]]]":
            try:
                return index, self.hosts[index].probe(prompt)
            except _DEAD_ERRORS:
                self._note_failure()
                return index, None

        with ThreadPoolExecutor(max_workers=len(indices)) as pool:
            results = list(pool.map(one, indices))
        return {index: probe for index, probe in results if probe is not None}

    # ------------------------------------------------------------------ submission

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        constraint: Optional[int] = None,
        deadline: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> "Iterator[np.ndarray]":
        """Route a prompt to the best live host and return its token stream
        (the engine submit contract, one level up)."""
        if expired(deadline):
            with self._lock:
                self.shed_deadline += 1
            raise DeadlineExceeded("deadline expired before the prompt was routed to a host")
        live = self._live()
        if not live:
            raise RuntimeError(f"all {len(self.hosts)} fleet hosts are dead")
        probes = self._probe_all(live, prompt)
        if not probes:
            raise RuntimeError(f"all {len(self.hosts)} fleet hosts are dead")
        kwargs = dict(
            max_new_tokens=max_new_tokens, constraint=constraint, deadline=deadline,
            tenant=tenant, priority=priority,
        )
        if any(self.hosts[i].role == "prefill" for i in probes):
            stream = self._submit_disaggregated(probes, prompt, kwargs)
            if stream is not None:
                return stream
        return self._submit_routed(probes, prompt, kwargs)

    def _order(
        self, probes: "Dict[int, Dict[str, Any]]", prompt: Sequence[int],
        tenant: Optional[str] = None,
    ) -> "Tuple[List[int], Any]":
        """The scheduler's host order over the full (stable-index) host list;
        dead/unprobed hosts rank last via infinite load + avoid flags and are
        filtered from the returned walk. ``tenant`` arms HOST-level tenant
        session affinity: when no host's radix probe is warm for this prompt,
        the host that last served the tenant heads the walk (margin-gated) —
        its radix tier holds the tenant's recent sessions."""
        n = len(self.hosts)
        loads = [probes[i]["load"] if i in probes else math.inf for i in range(n)]
        cached = [probes[i]["cached"] if i in probes else 0 for i in range(n)]
        breaching = [probes[i]["breaching"] if i in probes else True for i in range(n)]
        deprioritized = [self.hosts[i].role == "prefill" for i in range(n)]
        order, affinity_head = self._scheduler.order(
            loads, prompt,
            cached if max(cached, default=0) > 0 else None,
            breaching,
            deprioritized if any(deprioritized) else None,
            tenant=tenant,
        )
        return [i for i in order if i in probes], affinity_head

    def _submit_routed(
        self,
        probes: "Dict[int, Dict[str, Any]]",
        prompt: Sequence[int],
        kwargs: Dict[str, Any],
    ) -> "Iterator[np.ndarray]":
        tenant = kwargs.get("tenant")
        if tenant is None:
            from unionml_tpu.serving.tenancy import current_tenant

            tenant = current_tenant()
        order, affinity_head = self._order(probes, prompt, tenant)
        last_exc: Optional[BaseException] = None
        for index in order:
            try:
                stream = self.hosts[index].submit(prompt, **kwargs)
            except TenantThrottled:
                raise  # every host shares the tenant policy; the walk could only re-shed
            except QueueFullError as exc:
                last_exc = exc
                continue
            except _DEAD_ERRORS as exc:
                self._note_failure()
                last_exc = exc
                continue
            self._scheduler.note(
                index, prompt,
                affinity=affinity_head if index == order[0] else False,
                tenant=tenant,
            )
            return self._guard_stream(stream, index, prompt, kwargs)
        with self._lock:
            self.shed_queue_full += 1
        raise QueueFullError(
            f"all {len(order)} live hosts' queues are full"
        ) from last_exc

    def _guard_stream(
        self,
        stream: Any,
        index: int,
        prompt: Sequence[int],
        kwargs: Dict[str, Any],
    ) -> "Iterator[np.ndarray]":
        """The accepted-stream fault contract: a host that dies under a
        stream with ZERO tokens emitted costs one transparent retry on a
        sibling (the request never observably failed); a host that dies
        after tokens flowed terminates the stream with a clean 503-shaped
        :class:`StreamInterrupted` — the consumer learns NOW, instead of
        hanging on a dead socket or silently receiving a spliced stream with
        different sampling state."""
        emitted = 0
        retried = False
        recover_from: Optional[float] = None
        try:
            while True:
                try:
                    for chunk in stream:
                        if recover_from is not None:
                            self._recovery_ms.observe(time.monotonic() - recover_from)
                            recover_from = None
                        emitted += int(np.asarray(chunk).size)
                        yield chunk
                    return
                except (HostDied, *_DEAD_ERRORS) as exc:
                    self._note_failure()
                    failed_at = time.monotonic()
                    trace = _current_trace()
                    if trace is not None:
                        trace.event("engine.host_suspect", host=index, emitted=emitted)
                    if emitted > 0 or retried:
                        with self._lock:
                            self.streams_interrupted += 1
                        raise StreamInterrupted(
                            f"fleet host {index} failed after {emitted} emitted tokens: {exc}",
                            emitted=emitted,
                        ) from exc
                    retried = True
                    stream = self._retry_on_sibling(index, prompt, kwargs, exc)
                    recover_from = failed_at
                    index = getattr(stream, "_retry_host", index)
        finally:
            _close_quietly(stream)

    def _retry_on_sibling(
        self,
        failed_index: int,
        prompt: Sequence[int],
        kwargs: Dict[str, Any],
        cause: BaseException,
    ) -> Any:
        """Resubmit a zero-token stream on the best sibling host (once)."""
        live = [i for i in self._live() if i != failed_index]
        probes = self._probe_all(live, prompt) if live else {}
        if not probes:
            with self._lock:
                self.streams_interrupted += 1
            raise StreamInterrupted(
                f"fleet host {failed_index} died before the first token and no "
                "sibling is live",
                emitted=0,
            ) from cause
        order, _ = self._order(probes, prompt, kwargs.get("tenant"))
        last: Optional[BaseException] = None
        for sibling in order:
            try:
                stream = self.hosts[sibling].submit(prompt, **kwargs)
            except (QueueFullError, *_DEAD_ERRORS) as exc:
                last = exc
                continue
            with self._lock:
                self.stream_retries += 1
            trace = _current_trace()
            if trace is not None:
                trace.event("engine.stream_retry", host=sibling, failed_host=failed_index)
            self._scheduler.note(sibling, prompt, tenant=kwargs.get("tenant"))
            try:
                stream._retry_host = sibling
            except AttributeError:  # engine streams without a __dict__
                pass
            logger.info(
                f"stream retried on host {sibling} after host {failed_index} died "
                "with zero tokens emitted"
            )
            return stream
        with self._lock:
            self.streams_interrupted += 1
        raise StreamInterrupted(
            f"fleet host {failed_index} died before the first token and every "
            f"sibling refused the retry",
            emitted=0,
        ) from (last if last is not None else cause)

    # -------------------------------------------------------------- disaggregation

    def _submit_disaggregated(
        self,
        probes: "Dict[int, Dict[str, Any]]",
        prompt: Sequence[int],
        kwargs: Dict[str, Any],
    ) -> "Optional[Iterator[np.ndarray]]":
        """The cross-host prefill→decode path; None = not applicable (short
        prompt, no viable pair) — the caller falls back to the classic walk,
        so host disaggregation can only redirect work, never shed it."""
        prefills = [i for i in probes if self.hosts[i].role == "prefill"]
        targets = [i for i in probes if self.hosts[i].role == "decode"] or [
            i for i in probes if self.hosts[i].role == "mixed"
        ]
        if not prefills or not targets or len(prompt) < self._prefill_threshold:
            return None
        # warm multi-turn shortcut at host granularity: a decode host whose
        # radix tier already covers most of the prompt admits directly
        warm = max(targets, key=lambda i: (probes[i]["cached"], -probes[i]["load"]))
        cached = probes[warm]["cached"]
        if cached > 0 and len(prompt) - cached < max(self._prefill_threshold, (len(prompt) + 1) // 2):
            try:
                stream = self.hosts[warm].submit(prompt, **kwargs)
            except (QueueFullError, *_DEAD_ERRORS):
                pass
            else:
                self._scheduler.note(warm, prompt)
                return stream
        for p in sorted(prefills, key=lambda i: (probes[i]["load"], i)):
            try:
                pstream = self.hosts[p].submit(prompt, export=True, **kwargs)
            except (QueueFullError, *_DEAD_ERRORS) as exc:
                if isinstance(exc, _DEAD_ERRORS):
                    self._note_failure()
                continue
            self._scheduler.note(p, prompt)
            targets_ranked = sorted(targets, key=lambda i: (probes[i]["load"], i))
            return self._relay(pstream, targets_ranked)
        return None

    def _relay(self, pstream: Any, targets: "List[int]") -> "Iterator[np.ndarray]":
        """Stitch the prefill host's first-token stream and the decode host's
        resident stream into one consumer-facing iterator, shipping the
        block-native payload across the control plane in between."""
        active = pstream
        try:
            for item in pstream:
                yield item
            payload = getattr(pstream, "handoff", None)
            if payload is None:
                return  # finished outright at the prompt-sampled token
            started = time.monotonic()
            dstream = self._import_on(targets, payload)
            with self._lock:
                self.cross_host_handoffs += 1
            self._transfer_ms.observe(time.monotonic() - started)
            active = dstream
            for item in dstream:
                yield item
        finally:
            _close_quietly(active)

    def _import_on(self, targets: "List[int]", payload: Any) -> Any:
        last_exc: Optional[BaseException] = None
        for t in targets:
            try:
                return self.hosts[t].import_handoff(payload)
            except (QueueFullError, RuntimeError) as exc:
                last_exc = exc
                continue
            except _DEAD_ERRORS as exc:
                self._note_failure()
                last_exc = exc
                continue
        raise RuntimeError(
            f"no decode host of {len(targets)} could adopt the handed-off prefill"
        ) from last_exc

    # ------------------------------------------------------------- reconciliation

    def start_reconciler(self) -> None:
        """Start the background reconciliation loop: heartbeat the lease,
        watch the rendezvous dir for fresh (epoch-stamped) announces, re-probe
        suspect/dead hosts, and walk returning hosts through probation +
        warmup back to live. Idempotent; joined by :meth:`close`."""
        if self._reconcile_thread is not None:
            return
        self._reconcile_stop.clear()
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="unionml-tpu-fleet-reconcile"
        )
        self._reconcile_thread.start()

    def stop_reconciler(self) -> None:
        self._reconcile_stop.set()
        thread = self._reconcile_thread
        if thread is not None:
            thread.join(timeout=10.0)
            self._reconcile_thread = None

    def _reconcile_loop(self) -> None:
        while not self._reconcile_stop.wait(self._probe_interval_s):
            try:
                self.reconcile_once()
            except Exception:  # pragma: no cover - defensive
                logger.exception("fleet reconciliation tick failed")

    def reconcile_once(self) -> None:
        """One reconciliation tick (public so tests and single-shot callers
        can drive the state machine without the timer thread)."""
        self._heartbeat_lease()
        self._scan_announces()
        self._probe_unhealthy()

    def _heartbeat_lease(self) -> None:
        if self.fleet_dir is None:
            return
        ok = write_lease(
            self.fleet_dir, epoch=self.epoch, owner=0, ttl_s=self._lease_ttl_s
        )
        if not ok and not self.fenced:
            self.fenced = True
            logger.warning(
                f"coordinator epoch {self.epoch} is fenced: a successor holds a higher "
                "epoch; this coordinator's rendezvous writes are rejected"
            )

    def _scan_announces(self) -> None:
        """Bind returning workers: a rendezvous announce whose epoch clears
        the floor AND differs from what the handle is currently bound to is a
        restarted (or replacement) worker — rebind the handle into probation.
        Stale files from a previous fleet generation are ignored."""
        if self.fleet_dir is None or not self.fleet_dir.exists():
            return
        for path in sorted(self.fleet_dir.glob("host-*.json")):
            record = _read_json_file(path)
            if record is None:
                continue
            try:
                pid = int(record["process_id"])
                address = f"{record['host']}:{record['port']}"
                epoch = int(record.get("epoch", 0))
            except (KeyError, TypeError, ValueError):
                continue
            if epoch < self._announce_floor:
                continue  # a previous incarnation's leftovers
            host = next(
                (h for h in self.hosts if isinstance(h, RemoteHost) and h.host_id == pid),
                None,
            )
            if host is None or host.state == HOST_LIVE:
                continue
            candidate = (address, epoch, record.get("pid"))
            if host._bound_announce == candidate:
                continue  # the incarnation we already know (and failed) about
            host.rebind(address, epoch=epoch, role=record.get("role"))
            host._bound_announce = candidate

    def _probe_unhealthy(self) -> None:
        """Re-probe every non-live remote host: successes walk it through
        probation (then warmup, then live); failures demote suspect → dead
        after the configured streak. Never under a lock (TPU013)."""
        for host in self.hosts:
            if not isinstance(host, RemoteHost) or host.state == HOST_LIVE:
                continue
            try:
                host.ping(timeout=min(self._probe_interval_s * 4, CONTROL_TIMEOUT_S))
            except (_DEAD_ERRORS + (RuntimeError,)):
                host.note_probe_failure(self._dead_after)
                continue
            if not host.note_probe_success(self._probation_probes):
                continue
            try:
                # rejoin warmup: cheap when the worker preloads from the AOT
                # store (PR 12); a failure here is just another failed probe
                host.warmup()
            except (_DEAD_ERRORS + (RuntimeError,)):
                host.note_probe_failure(self._dead_after)
                continue
            changed, down = host.go_live()
            if changed and down is not None:
                self._recovery_ms.observe(time.monotonic() - down)

    # ------------------------------------------------------------------ fleet ops

    def warmup(self) -> None:
        """Warm every live host concurrently (each host warms its own
        replicas in parallel below this)."""
        from concurrent.futures import ThreadPoolExecutor

        live = [self.hosts[i] for i in self._live()]
        with ThreadPoolExecutor(max_workers=max(len(live), 1)) as pool:
            list(pool.map(lambda host: host.warmup(), live))

    def load(self) -> float:
        total = 0.0
        for index in self._live():
            try:
                total += float(self.hosts[index].probe(None)["load"])
            except _DEAD_ERRORS:
                self._note_failure()
        return total

    def cached_prefix_tokens(self, prompt: Sequence[int]) -> int:
        """Fleet-global radix probe (a coordinator can itself be a host of a
        higher-level fleet)."""
        best = 0
        for index in self._live():
            try:
                best = max(best, int(self.hosts[index].probe(prompt)["cached"]))
            except _DEAD_ERRORS:
                self._note_failure()
        return best

    def occupancy(self) -> "Tuple[int, int]":
        resident = waiting = 0
        for index in self._live():
            try:
                r, w = self.hosts[index].occupancy()
            except _DEAD_ERRORS:
                self._note_failure()
                continue
            resident += r
            waiting += w
        return resident, waiting

    def scale_to(self, n: int, *, role: Optional[str] = None, timeout: float = 120.0) -> int:
        """Resize the FLEET to ``n`` total replicas, spread evenly over live
        hosts (stable order, remainder to the lowest host ids). Each host's
        own ``scale_to`` does the zero-loss work — warm-before-join on the
        way up, quiesce-drain-close on the way down."""
        live = self._live()
        if not live:
            raise RuntimeError("no live hosts to scale")
        if n < len(live):
            raise ValueError(
                f"a {len(live)}-host fleet cannot scale below one replica per host ({len(live)})"
            )
        base, rem = divmod(int(n), len(live))
        total = 0
        for position, index in enumerate(live):
            target = base + (1 if position < rem else 0)
            try:
                total += self.hosts[index].scale_to(target, role=role)
            except _DEAD_ERRORS:
                self._note_failure()
        return total

    def health(self) -> Dict[str, Any]:
        """Fleet health at host granularity — same shape as
        :func:`~unionml_tpu.observability.health.fleet_health` (which this
        delegates to through the ``batchers`` duck-typing), so ``/healthz``
        renders a multi-host fleet with per-host rows unchanged."""
        from unionml_tpu.observability.health import fleet_health

        return fleet_health(self)

    def replica_loads(self) -> "List[Dict[str, Any]]":
        """Per-host occupancy rows for live gauges (`/debug/fleet`)."""
        out = []
        for index, host in enumerate(self.hosts):
            row: Dict[str, Any] = {
                "host": index, "role": host.role, "alive": host.alive,
                "address": host.address, "state": _host_state(host),
                "last_transition_s": _host_transition_s(host),
            }
            if host.alive:
                try:
                    resident, waiting = host.occupancy()
                    row.update({"resident": resident, "waiting": waiting})
                except _DEAD_ERRORS:
                    self._note_failure()
            out.append(row)
        return out

    def host_census(self) -> "List[Dict[str, Any]]":
        """The ``/debug/fleet`` host table: who is where, alive, what role,
        how many replicas."""
        return [
            {
                "host": index,
                "process_id": getattr(host, "host_id", index),
                "address": host.address,
                "role": host.role,
                "alive": host.alive,
                "state": _host_state(host),
                "last_transition_s": _host_transition_s(host),
                "replicas": host.replicas() if host.alive else 0,
            }
            for index, host in enumerate(self.hosts)
        ]

    def stats(self) -> Dict[str, Any]:
        """Fleet snapshot for ``/metrics``: per-host sections plus the
        cross-host aggregates and the coordinator's own routing/failure
        telemetry."""
        per_host: "List[Dict[str, Any]]" = []
        for index, host in enumerate(self.hosts):
            entry: Dict[str, Any] = {
                "host": index,
                "process_id": getattr(host, "host_id", index),
                "address": host.address,
                "role": host.role,
                "alive": host.alive,
                "state": _host_state(host),
                "last_transition_s": _host_transition_s(host),
            }
            if host.alive:
                try:
                    entry["stats"] = host.stats()
                except _DEAD_ERRORS:
                    self._note_failure()
                    entry["alive"] = False
                    entry["state"] = _host_state(host)
            per_host.append(entry)

        def total(key: str) -> int:
            return sum(
                int((entry.get("stats") or {}).get(key) or 0) for entry in per_host
            )

        states: Dict[str, int] = {
            HOST_LIVE: 0, HOST_SUSPECT: 0, HOST_DEAD: 0, HOST_PROBATION: 0
        }
        for entry in per_host:
            states[entry["state"]] = states.get(entry["state"], 0) + 1
        with self._lock:
            shed_deadline, shed_queue_full = self.shed_deadline, self.shed_queue_full
            host_failures = self.host_failures
            cross_host = self.cross_host_handoffs
            stream_retries = self.stream_retries
            streams_interrupted = self.streams_interrupted
            failovers = self.coordinator_failovers
        fleet: Dict[str, Any] = {
            "epoch": int(self.epoch),
            "fenced": int(self.fenced),
            "host_suspects": sum(int(getattr(h, "suspects", 0)) for h in self.hosts),
            "host_rejoins": sum(int(getattr(h, "rejoins", 0)) for h in self.hosts),
            "rpc_retries": sum(int(getattr(h, "rpc_retries", 0)) for h in self.hosts),
            "coordinator_failovers": failovers,
            "stream_retries": stream_retries,
            "streams_interrupted": streams_interrupted,
            "recovery_ms": self._recovery_ms.snapshot(),
            "states": states,
        }
        if self._faults is not None:
            fleet["faults_injected"] = self._faults.stats()
        return {
            "hosts": per_host,
            "live_hosts": sum(1 for entry in per_host if entry["alive"]),
            "replicas": total("replicas"),
            "scheduler": self._scheduler.stats(),
            "host_failures": host_failures,
            "handoffs_cross_host": cross_host,
            "handoff_transfer_ms": self._transfer_ms.snapshot(),
            "fleet": fleet,
            "slots": total("slots"),
            "resident": total("resident"),
            "waiting": total("waiting"),
            "decode_dispatches": total("decode_dispatches"),
            "decoded_rows": total("decoded_rows"),
            "shed_queue_full": shed_queue_full + total("shed_queue_full"),
            "shed_deadline": shed_deadline + total("shed_deadline"),
        }

    def close(self, wait: bool = True, timeout: float = 120.0,
              *, shutdown_workers: bool = False) -> None:
        """Drain every live host (``shutdown_workers=True`` also stops the
        worker processes' control loops — the CLI-owned fleet's exit path;
        test-owned workers are reaped by their spawner). The reconciliation
        thread is stopped and joined first (TPU008)."""
        self.stop_reconciler()
        for index in self._live():
            try:
                self.hosts[index].close(shutdown_worker=shutdown_workers)
            except _DEAD_ERRORS:
                self._note_failure()


# -------------------------------------------------------------------- fleet bootstrap


def connect_fleet(
    fleet_dir: "str | Path | None" = None,
    *,
    num_hosts: int,
    timeout_s: float = 120.0,
    local_engine: Any = None,
    local_process_id: int = 0,
    epoch: Optional[int] = None,
    announce_floor: Optional[int] = None,
    allow_missing: bool = False,
    start_reconciler: bool = True,
    **coordinator_kwargs: Any,
) -> FleetCoordinator:
    """Build a :class:`FleetCoordinator` from the rendezvous directory the
    workers announce into: poll until ``num_hosts`` announcements appear (a
    worker that never announces fails the connect loudly at ``timeout_s``),
    ping each worker, and return the coordinator with hosts in process-id
    order. ``local_engine`` substitutes a direct in-process handle for
    ``local_process_id`` (host 0 usually serves too — its submissions
    shouldn't pay an HTTP hop).

    Failover semantics: the new coordinator's fencing ``epoch`` is the
    persisted checkpoint's epoch plus one (or the explicit ``epoch``), a
    fenced checkpoint + heartbeat lease are written before returning, and
    announces stamped with an epoch BELOW the previous checkpoint's are
    ignored as a previous fleet generation's leftovers. ``allow_missing``
    (the promotion path) builds dead placeholder handles for hosts that
    never announced or failed their connect ping — the reconciliation loop
    (started unless ``start_reconciler=False``) readmits them if they
    return."""
    root = Path(fleet_dir if fleet_dir is not None else default_fleet_dir()).expanduser()
    previous = read_checkpoint(root)
    prev_epoch = int(previous.get("epoch", 0)) if previous else 0
    # a FRESH connect starts a new generation: only announces stamped from
    # the previous checkpoint onward count. A same-generation successor
    # (maybe_promote) passes the generation's original floor instead.
    floor = prev_epoch if announce_floor is None else int(announce_floor)
    my_epoch = (prev_epoch + 1) if epoch is None else int(epoch)
    deadline = time.monotonic() + timeout_s
    announcements: "Dict[int, Dict[str, Any]]" = {}
    while True:
        if root.exists():
            for path in sorted(root.glob("host-*.json")):
                try:
                    record = json.loads(path.read_text())
                except (OSError, ValueError):
                    continue  # half-written or vanished; next poll sees it
                if int(record.get("epoch", 0)) < floor:
                    continue  # stale: a previous fleet generation's announce
                announcements[int(record["process_id"])] = record
        needed = set(range(num_hosts))
        if local_engine is not None:
            needed.discard(local_process_id)
        if needed <= set(announcements):
            break
        if time.monotonic() >= deadline:
            if allow_missing and (announcements or local_engine is not None):
                break
            raise TimeoutError(
                f"fleet rendezvous timed out: {sorted(announcements)} of {num_hosts} "
                f"hosts announced in {root}"
            )
        time.sleep(0.05)
    hosts: "List[Any]" = []
    for process_id in range(num_hosts):
        if local_engine is not None and process_id == local_process_id:
            hosts.append(LocalHost(local_engine, host_id=process_id))
            continue
        record = announcements.get(process_id)
        if record is None:
            # allow_missing promotion path: a placeholder the reconciler can
            # readmit when (if) the host announces again
            host = RemoteHost("0.0.0.0:0", host_id=process_id)
            host.mark_dead(ConnectionError("never announced for this epoch"))
            hosts.append(host)
            continue
        host = RemoteHost(
            f"{record['host']}:{record['port']}",
            host_id=process_id,
            role=record.get("role", "mixed"),
            epoch=int(record.get("epoch", 0)),
        )
        host._bound_announce = (
            host.address, host.epoch, record.get("pid")
        )
        try:
            host.ping()  # fail the connect loudly rather than at first routing
        except _DEAD_ERRORS:
            if not allow_missing:
                raise
            host.mark_dead(ConnectionError("connect ping failed"))
        hosts.append(host)
    coordinator = FleetCoordinator(
        hosts, fleet_dir=root, epoch=my_epoch, **coordinator_kwargs
    )
    coordinator._announce_floor = floor
    roster = [
        {
            "host": index,
            "process_id": getattr(host, "host_id", index),
            "address": host.address,
            "role": host.role,
            "alive": host.alive,
        }
        for index, host in enumerate(hosts)
    ]
    failovers = int(previous.get("failovers", 0)) if previous else 0
    if not write_checkpoint(
        root, epoch=my_epoch, num_hosts=num_hosts, roster=roster,
        failovers=failovers, announce_floor=floor,
    ) or not write_lease(
        root, epoch=my_epoch, owner=local_process_id, ttl_s=coordinator._lease_ttl_s
    ):
        coordinator.fenced = True
        logger.warning(
            f"connect_fleet epoch {my_epoch} lost the fencing race: a higher-epoch "
            "coordinator already owns this rendezvous dir"
        )
    coordinator.coordinator_failovers = failovers
    if start_reconciler:
        coordinator.start_reconciler()
    return coordinator


def maybe_promote(
    fleet_dir: "str | Path | None" = None,
    *,
    local_engine: Any,
    local_process_id: int,
    num_hosts: Optional[int] = None,
    lease_grace_s: float = 0.0,
    timeout_s: float = 10.0,
    **coordinator_kwargs: Any,
) -> "Optional[FleetCoordinator]":
    """Coordinator failover: promote THIS worker if (and only if) the
    coordinator lease has expired and no lower-id live worker outranks it.

    Returns ``None`` while the lease is fresh or a better candidate exists;
    otherwise connects a new :class:`FleetCoordinator` over the surviving
    announces with the checkpoint epoch BUMPED — the fencing edge: the old
    coordinator's subsequent checkpoint/lease writes are rejected, and
    accepted-but-unfinished streams on surviving hosts are untouched (this is
    pure control-plane succession; no engine state moves)."""
    root = Path(fleet_dir if fleet_dir is not None else default_fleet_dir()).expanduser()
    lease = read_lease(root)
    if not lease_expired(lease, grace_s=lease_grace_s):
        return None
    checkpoint = read_checkpoint(root)
    if num_hosts is None:
        if checkpoint is None:
            return None  # nothing to succeed: no fleet ever checkpointed here
        num_hosts = int(checkpoint.get("num_hosts", 0))
    prev_epoch = int(checkpoint.get("epoch", 0)) if checkpoint else 0
    # the succession stays WITHIN the dead coordinator's fleet generation:
    # the generation's original announces (stamped at its formation floor)
    # remain valid for the successor
    floor = int(checkpoint.get("announce_floor", 0)) if checkpoint else 0
    # lowest-id-live-wins: a smaller-id worker with a current-generation
    # announce that still answers its ping has precedence — stand down for it
    for path in sorted(root.glob("host-*.json")):
        record = _read_json_file(path)
        if record is None:
            continue
        pid = int(record.get("process_id", -1))
        if not (0 <= pid < local_process_id) or int(record.get("epoch", 0)) < floor:
            continue
        probe = RemoteHost(f"{record['host']}:{record['port']}", host_id=pid)
        try:
            probe.ping(timeout=2.0)
        except _DEAD_ERRORS:
            continue
        return None
    coordinator = connect_fleet(
        root,
        num_hosts=int(num_hosts),
        timeout_s=timeout_s,
        local_engine=local_engine,
        local_process_id=int(local_process_id),
        epoch=prev_epoch + 1,
        announce_floor=floor,
        allow_missing=True,
        **coordinator_kwargs,
    )
    coordinator.coordinator_failovers += 1
    write_checkpoint(
        root,
        epoch=coordinator.epoch,
        num_hosts=int(num_hosts),
        roster=[
            {
                "host": index,
                "process_id": getattr(host, "host_id", index),
                "address": host.address,
                "role": host.role,
                "alive": host.alive,
            }
            for index, host in enumerate(coordinator.hosts)
        ],
        failovers=coordinator.coordinator_failovers,
        announce_floor=floor,
    )
    logger.warning(
        f"worker {local_process_id} promoted to fleet coordinator "
        f"(epoch {coordinator.epoch}, failover #{coordinator.coordinator_failovers})"
    )
    return coordinator


def run_worker(spec: Dict[str, Any]) -> None:
    """A worker process's whole life (the ``python -m
    unionml_tpu.serving.cluster`` entrypoint body):

    1. join the jax.distributed runtime named by the env
       (:func:`unionml_tpu.distributed.maybe_initialize` — the bootstrap
       shared with ``job_runner``);
    2. AGREE on the fleet config: process 0's ``builder``/``kwargs`` are
       broadcast over ``multihost_utils`` and every host builds from the
       agreed copy — knob-identical engines by construction, not by hope;
    3. build the local engine (the builder returns a ContinuousBatcher or
       ReplicaSet over this host's devices) and fence at a barrier so no
       host announces before the slowest finishes building;
    4. exchange control ports (``process_allgather``), start the
       :class:`WorkerAgent`, announce into the fleet dir, and serve until
       ``/ctrl/shutdown`` (or SIGTERM) arrives.
    """
    from unionml_tpu import distributed
    from unionml_tpu.resolver import locate

    distributed.maybe_initialize()
    agreed = distributed.agree(
        {"builder": spec["builder"], "kwargs": spec.get("kwargs") or {}}
    )
    if agreed["builder"] != spec["builder"]:
        logger.warning(
            f"fleet config disagreement: host 0 builds {agreed['builder']!r}, this spec "
            f"names {spec['builder']!r}; building host 0's (the agreement wins)"
        )
    builder = locate(agreed["builder"])
    engine = builder(**agreed["kwargs"])
    distributed.barrier("unionml-tpu-fleet-build")
    agent = WorkerAgent(
        engine,
        host=spec.get("control_host", "127.0.0.1"),
        role=spec.get("role", "mixed"),
    )
    agent.start()
    ports = distributed.allgather_ints(agent.port)
    logger.info(f"fleet control ports by process: {ports}")
    fleet_dir = spec.get("fleet_dir") or default_fleet_dir()
    agent.announce(fleet_dir)
    #: with watch_lease set, this worker is a failover STANDBY: when the
    #: coordinator's heartbeat lease expires, the lowest-id live worker
    #: promotes itself (fencing the old epoch) so the fleet's control
    #: metadata — checkpoint, lease, rendezvous hygiene — survives
    watch_lease = bool(spec.get("watch_lease"))
    promoted: "Optional[FleetCoordinator]" = None
    next_lease_check = time.monotonic() + fleet_lease_ttl_s()
    try:
        while not agent.shutdown_event.wait(0.2):
            if watch_lease and promoted is None and time.monotonic() >= next_lease_check:
                next_lease_check = time.monotonic() + fleet_lease_ttl_s()
                try:
                    promoted = maybe_promote(
                        fleet_dir,
                        local_engine=engine,
                        local_process_id=agent.process_id,
                    )
                except Exception:  # pragma: no cover - defensive
                    logger.exception("coordinator promotion attempt failed")
    finally:
        if promoted is not None:
            promoted.stop_reconciler()
        agent.close(close_engine=True)


def enable_serve_cluster(serving: Any, *, host: str = "127.0.0.1", port: int = 8000) -> None:
    """Run a :class:`~unionml_tpu.serving.app.ServingApp` as one member of a
    multi-host fleet (the ``serve --num-hosts/--coordinator/--process-id``
    path). Process 0 is the front door: its ``model.generation_batcher`` is
    wrapped in a :class:`FleetCoordinator` (itself as the local host, every
    peer as a remote one) and the public HTTP server runs as usual — so
    ``/predict-stream``, ``/v1/*``, ``/metrics``, ``/healthz``,
    ``/debug/fleet`` and ``/debug/scale`` all operate on the whole fleet.
    Processes > 0 run only the control server: their engines take work from
    the coordinator, not from clients."""
    from unionml_tpu import distributed

    distributed.maybe_initialize()
    me, num = distributed.process_index(), distributed.process_count()
    serving.startup()
    engine = getattr(serving.model, "generation_batcher", None)
    if engine is None:
        raise RuntimeError(
            "cluster serving needs a generation engine: set model.generation_batcher "
            "(e.g. the text-generation template's ContinuousBatcher/ReplicaSet) "
            "before serve starts"
        )
    fleet = default_fleet_dir()
    if me != 0:
        agent = WorkerAgent(engine)
        agent.start()
        ports = distributed.allgather_ints(agent.port)
        logger.info(f"fleet control ports by process: {ports}")
        agent.announce(fleet)
        try:
            while not agent.shutdown_event.wait(0.2):
                pass
        finally:
            agent.close(close_engine=True)
        return
    # process 0: rendezvous with every worker, then serve the front door
    distributed.allgather_ints(0)  # pair the workers' port exchange
    coordinator = connect_fleet(
        fleet, num_hosts=num, local_engine=engine, local_process_id=0
    )
    serving.model.generation_batcher = coordinator
    serving.run(host=host, port=port)


def main(argv: "Optional[List[str]]" = None) -> None:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m unionml_tpu.serving.cluster",
        description="run one multi-host serving fleet worker from a spec file",
    )
    parser.add_argument("spec", help="path to the worker spec JSON (builder, kwargs, fleet_dir, role)")
    args = parser.parse_args(argv)
    run_worker(json.loads(Path(args.spec).read_text()))


if __name__ == "__main__":
    main()
