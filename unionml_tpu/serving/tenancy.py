"""Multi-tenant QoS: tenant identity, per-tenant token buckets, fair shares.

The north star is "heavy traffic from millions of users" — and until now every
request was anonymous and equal: overload shedding (serving/overload.py) is
global, replica routing is load-only, and one hostile client bursting requests
FIFO-starves everyone behind it. This module is the tenancy layer the rest of
the serving stack keys on:

- **identity**: :func:`resolve_tenant` extracts a tenant id from the request
  headers — ``X-Tenant-Id`` wins; else an ``Authorization: Bearer`` key maps
  through the registry's ``api_keys`` table, or (unmapped) derives a stable
  non-reversible id from the key's digest, so the OpenAI SDK's ``api_key`` IS
  the tenant identity without the secret ever reaching traces or metrics. The
  id and the ``X-Priority`` tier ride contextvars down the stack exactly like
  the PR 5 request id;
- **rate limits**: :class:`TenantRegistry` holds per-tenant token buckets —
  requests/s and generated-tokens/s, lazily refilled — in a BOUNDED map with
  idle eviction (the registry dogfoods tpu-lint TPU009: a tenant-keyed dict
  must have an eviction path). A bucket miss sheds with
  :class:`~unionml_tpu.serving.overload.TenantThrottled` (HTTP 429) whose
  ``Retry-After`` is computed from that bucket's actual refill time;
- **fair shares**: per-tenant ``weight`` drives the continuous engine's
  deficit-round-robin admission (serving/continuous.py) so a burst from one
  tenant no longer starves the rest, and ``priority`` sets a request's default
  tier (``high``/``normal``/``batch``) — a high-priority admission may preempt
  a lowest-priority resident through the engine's existing paged
  preempt/exact-width-resume machinery (the preempted stream resumes
  token-identically, never truncates).

Zero-cost off contract: with no registry installed and no tenancy headers,
every request runs with ``current_tenant()`` and ``current_priority()`` both
``None``, the engine's admission stays plain FIFO, and no stats section or
trace attribute changes — byte-for-byte today's serving stack (the same
contract every serve-time knob in this repo holds to).

Anonymous traffic (no tenant headers) is never bucket-limited — it rides the
global overload posture (PR 1) — but it does participate in the fair-share
round as one pseudo-tenant, so identified tenants cannot starve it either.
"""

from __future__ import annotations

import contextvars
import dataclasses
import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from unionml_tpu._logging import logger

__all__ = [
    "PRIORITIES",
    "PRIORITY_BATCH",
    "PRIORITY_HIGH",
    "PRIORITY_NORMAL",
    "TenantRegistry",
    "TenantSpec",
    "active_registry",
    "bind_tenant",
    "current_priority",
    "current_tenant",
    "priority_name",
    "resolve_tenant",
    "sanitize_tenant_id",
    "set_active_registry",
    "unbind_tenant",
]

#: the wire headers (lower-cased, the serving stack's header-dict convention)
TENANT_HEADER = "x-tenant-id"
PRIORITY_HEADER = "x-priority"
AUTHORIZATION_HEADER = "authorization"

#: priority tiers, ordered: LOWER value = served (and preempts) first
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_BATCH = 2
PRIORITIES: "Dict[str, int]" = {
    "high": PRIORITY_HIGH,
    "normal": PRIORITY_NORMAL,
    "batch": PRIORITY_BATCH,
}
_PRIORITY_NAMES = {v: k for k, v in PRIORITIES.items()}

#: tenant ids echo into traces, metrics names, and debug payloads — same
#: sanitization posture as request ids (trace.py)
_MAX_TENANT_LEN = 64

_tenant_var: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "unionml_tpu_tenant", default=None
)
_priority_var: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "unionml_tpu_priority", default=None
)


def current_tenant() -> Optional[str]:
    """The tenant id of the request currently being handled (contextvar)."""
    return _tenant_var.get()


def current_priority() -> Optional[int]:
    """The priority tier of the active request (``None`` = unset/normal)."""
    return _priority_var.get()


def bind_tenant(tenant: Optional[str], priority: Optional[int]) -> "Tuple[Any, Any]":
    """Set the tenant/priority contextvars; returns reset tokens for
    :func:`unbind_tenant`. Called by the HTTP layer around each handler."""
    return _tenant_var.set(tenant), _priority_var.set(priority)


def unbind_tenant(tokens: "Tuple[Any, Any]") -> None:
    _tenant_var.reset(tokens[0])
    _priority_var.reset(tokens[1])


def priority_name(priority: Optional[int]) -> str:
    return _PRIORITY_NAMES.get(
        PRIORITY_NORMAL if priority is None else priority, "normal"
    )


def parse_priority(raw: str) -> int:
    """An ``X-Priority`` header value -> tier; raises ``ValueError`` on
    garbage (an explicit bad header is a usage error, not something to guess)."""
    tier = PRIORITIES.get(raw.strip().lower())
    if tier is None:
        raise ValueError(
            f"unknown priority {raw!r}; expected one of {sorted(PRIORITIES)}"
        )
    return tier


def sanitize_tenant_id(raw: Optional[str]) -> Optional[str]:
    """An inbound tenant id made safe to echo into headers/metrics/traces:
    same character policy as request ids, bounded length."""
    from unionml_tpu.observability.trace import sanitize_request_id

    kept = sanitize_request_id(raw)
    return kept[:_MAX_TENANT_LEN] if kept else None


def resolve_tenant(
    headers: "Dict[str, str]", registry: "Optional[TenantRegistry]" = None
) -> Optional[str]:
    """Tenant identity from request headers. ``X-Tenant-Id`` (sanitized) wins;
    else an ``Authorization: Bearer <key>`` maps through the registry's
    ``api_keys`` table when one is configured, falling back to a stable
    digest-derived id (``key-<12 hex>``) so distinct API keys become distinct
    tenants WITHOUT the secret itself ever reaching traces or metrics.
    ``None`` = anonymous."""
    explicit = sanitize_tenant_id(headers.get(TENANT_HEADER))
    if explicit:
        return explicit
    auth = headers.get(AUTHORIZATION_HEADER)
    if not auth:
        return None
    scheme, _, credential = auth.strip().partition(" ")
    credential = credential.strip()
    if scheme.lower() != "bearer" or not credential:
        return None
    if registry is not None:
        mapped = registry.tenant_for_key(credential)
        if mapped is not None:
            return mapped
    digest = hashlib.sha256(credential.encode("utf-8", "replace")).hexdigest()[:12]
    return f"key-{digest}"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's QoS contract.

    ``weight`` is the fair share driving deficit-round-robin admission (0 =
    best-effort: served only when no weighted tenant is waiting in the same
    tier). ``req_per_s``/``tokens_per_s`` are bucket refill rates (0 =
    unlimited); ``burst_s`` sizes each bucket's capacity as ``rate * burst_s``
    (never below one request / one token, so a conforming tenant is never shed
    from a cold start). ``priority`` is the DEFAULT tier for the tenant's
    requests — an explicit ``X-Priority`` header always wins.

    ``slo_ttft_p95_ms``/``slo_tbt_p99_ms``/``slo_shed_ratio`` are optional
    PER-TENANT SLO targets (docs/observability.md "SLOs and fleet health"):
    when any is set, every continuous engine keys a per-tenant burn-rate
    tracker for this tenant (bounded LRU — the TPU009 discipline), its
    verdicts ride ``stats()["tenant_slo"]`` → ``/metrics`` and ``/healthz``,
    and the traffic replayer judges the tenant against the same numbers.
    ``None``/0 = no per-tenant target (the tenant rides the engine-level SLO
    alone — byte-for-byte today's behavior)."""

    weight: float = 1.0
    req_per_s: float = 0.0
    tokens_per_s: float = 0.0
    burst_s: float = 2.0
    priority: str = "normal"
    slo_ttft_p95_ms: Optional[float] = None
    slo_tbt_p99_ms: Optional[float] = None
    slo_shed_ratio: Optional[float] = None

    def __post_init__(self) -> None:
        if self.weight < 0:
            raise ValueError("tenant weight must be >= 0")
        if self.req_per_s < 0 or self.tokens_per_s < 0:
            raise ValueError("tenant rates must be >= 0 (0 = unlimited)")
        if self.burst_s <= 0:
            raise ValueError("burst_s must be > 0")
        if self.priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {self.priority!r}; expected one of {sorted(PRIORITIES)}"
            )
        for name in ("slo_ttft_p95_ms", "slo_tbt_p99_ms", "slo_shed_ratio"):
            value = getattr(self, name)
            if value is not None and value < 0:
                raise ValueError(f"tenant {name} must be >= 0 (None/0 = disarmed)")

    def slo_config(self) -> "Optional[Any]":
        """This tenant's targets as an
        :class:`~unionml_tpu.observability.slo.SLOConfig` (windows/min-samples
        from the serve-wide ``UNIONML_TPU_SLO_*`` exports, so per-tenant and
        engine-level evaluation share one burn-rate clock); ``None`` when no
        per-tenant objective is armed — no tracker is ever created for such a
        tenant, which is what keeps target-less registries byte-for-byte
        off."""
        if not any((self.slo_ttft_p95_ms, self.slo_tbt_p99_ms, self.slo_shed_ratio)):
            return None
        from unionml_tpu.observability.slo import SLOConfig

        base = SLOConfig.from_env()
        return SLOConfig(
            ttft_p95_ms=self.slo_ttft_p95_ms or None,
            tbt_p99_ms=self.slo_tbt_p99_ms or None,
            shed_ratio=self.slo_shed_ratio or None,
            fast_window_s=base.fast_window_s,
            slow_window_s=base.slow_window_s,
            min_samples=base.min_samples,
        )


class _TenantState:
    """One tenant's live buckets + counters (registry lock guards access)."""

    __slots__ = (
        "spec", "req_tokens", "gen_tokens", "last_refill", "last_seen",
        "admitted", "shed", "generated_tokens", "refunded",
    )

    def __init__(self, spec: TenantSpec, now: float):
        self.spec = spec
        self.req_tokens = max(spec.req_per_s * spec.burst_s, 1.0)
        self.gen_tokens = max(spec.tokens_per_s * spec.burst_s, 1.0)
        self.last_refill = now
        self.last_seen = now
        self.admitted = 0
        self.shed = 0
        self.generated_tokens = 0
        self.refunded = 0

    def refill(self, now: float) -> None:
        elapsed = max(now - self.last_refill, 0.0)
        self.last_refill = now
        if self.spec.req_per_s > 0:
            cap = max(self.spec.req_per_s * self.spec.burst_s, 1.0)
            self.req_tokens = min(cap, self.req_tokens + elapsed * self.spec.req_per_s)
        if self.spec.tokens_per_s > 0:
            cap = max(self.spec.tokens_per_s * self.spec.burst_s, 1.0)
            self.gen_tokens = min(cap, self.gen_tokens + elapsed * self.spec.tokens_per_s)


class TenantRegistry:
    """Per-tenant QoS state: specs, token buckets, counters — bounded.

    ``tenants`` maps names to :class:`TenantSpec`; any OTHER identified tenant
    gets ``default_spec`` (the ``serve --default-tenant-rate`` contract).
    ``api_keys`` maps ``Authorization: Bearer`` credentials to tenant names.
    The live state map is bounded at ``max_tenants`` with least-recently-SEEN
    eviction (plus ``idle_evict_s`` aging on every admission), so unbounded
    tenant-id cardinality — a scanner minting fresh ids per request — cannot
    grow host memory: exactly the bug class tpu-lint TPU009 exists for, and
    this map is its dogfood. Thread-safe; ``clock`` injectable for tests."""

    def __init__(
        self,
        tenants: "Optional[Dict[str, TenantSpec]]" = None,
        *,
        default_spec: Optional[TenantSpec] = None,
        api_keys: "Optional[Dict[str, str]]" = None,
        max_tenants: int = 256,
        idle_evict_s: float = 3600.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        if idle_evict_s <= 0:
            raise ValueError("idle_evict_s must be > 0")
        self.specs: "Dict[str, TenantSpec]" = dict(tenants or {})
        self.default_spec = default_spec if default_spec is not None else TenantSpec()
        self._api_keys: "Dict[str, str]" = dict(api_keys or {})
        self.max_tenants = max_tenants
        self.idle_evict_s = idle_evict_s
        self._clock = clock
        self._lock = threading.Lock()
        #: tenant -> live state, least-recently-seen first (move_to_end on
        #: every touch; eviction pops from the front)
        self._states: "OrderedDict[str, _TenantState]" = OrderedDict()
        self.evicted = 0

    # ------------------------------------------------------------------ config

    @classmethod
    def from_file(
        cls, path: str, *, default_rate: float = 0.0, **kwargs: Any
    ) -> "TenantRegistry":
        """Build from a ``tenants.json``::

            {
              "default": {"req_per_s": 10, "weight": 1},
              "tenants": {
                "acme":    {"weight": 2, "req_per_s": 50, "tokens_per_s": 2000},
                "batchco": {"weight": 0, "req_per_s": 5, "priority": "batch"}
              },
              "api_keys": {"sk-acme-123": "acme"}
            }

        ``default_rate`` (the ``--default-tenant-rate`` flag) fills the
        default spec's ``req_per_s`` when the file declares no ``default``."""
        with open(path) as handle:
            raw = json.load(handle)
        if not isinstance(raw, dict):
            raise ValueError(f"tenant config {path} must be a JSON object")
        tenants = {
            str(name): TenantSpec(**spec)
            for name, spec in (raw.get("tenants") or {}).items()
        }
        default_raw = raw.get("default")
        if default_raw is not None:
            default_spec = TenantSpec(**default_raw)
        else:
            default_spec = TenantSpec(req_per_s=float(default_rate))
        api_keys = {str(k): str(v) for k, v in (raw.get("api_keys") or {}).items()}
        return cls(tenants, default_spec=default_spec, api_keys=api_keys, **kwargs)

    @classmethod
    def from_env(cls) -> "Optional[TenantRegistry]":
        """The serve-time registry from the early-export env contract
        (``UNIONML_TPU_TENANT_CONFIG`` / ``_DEFAULT_TENANT_RATE``); ``None``
        when neither is set — tenancy off. A bad config file warns and falls
        back to rate-only (an inherited fleet-wide export must not crash
        serve at app-import time, the established degrade posture)."""
        from unionml_tpu.defaults import serve_default_tenant_rate, serve_tenant_config

        path = serve_tenant_config()
        rate = serve_default_tenant_rate()
        if path is None and rate <= 0:
            return None
        if path is not None:
            try:
                return cls.from_file(path, default_rate=rate)
            except (OSError, ValueError, TypeError) as exc:
                logger.warning(
                    f"ignoring tenant config {path!r} ({exc}); falling back to "
                    f"--default-tenant-rate={rate} only"
                )
        return cls(default_spec=TenantSpec(req_per_s=rate))

    def tenant_for_key(self, credential: str) -> Optional[str]:
        return self._api_keys.get(credential)

    def spec(self, tenant: Optional[str]) -> TenantSpec:
        if tenant is None:
            return self.default_spec
        return self.specs.get(tenant, self.default_spec)

    def weight(self, tenant: Optional[str]) -> float:
        """The fair-share weight the engine's deficit-round-robin uses;
        anonymous traffic rounds at weight 1 (it cannot be starved either)."""
        if not tenant:
            return 1.0
        return self.spec(tenant).weight

    def default_priority(self, tenant: Optional[str]) -> int:
        return PRIORITIES[self.spec(tenant).priority]

    # ------------------------------------------------------------------ buckets

    def _state_locked(self, tenant: str, now: float) -> _TenantState:
        state = self._states.get(tenant)
        if state is None:
            state = _TenantState(self.spec(tenant), now)
            self._states[tenant] = state
            self._evict_locked(now)
        else:
            state.last_seen = now
        self._states.move_to_end(tenant)
        return state

    def _evict_locked(self, now: float) -> None:
        """Bound the state map: drop idle tenants past ``idle_evict_s``, then
        least-recently-seen entries beyond ``max_tenants`` (their counters
        restart if they return — bounded memory beats perfect lifetime
        totals)."""
        while self._states:
            tenant, state = next(iter(self._states.items()))
            if now - state.last_seen > self.idle_evict_s:
                self._states.pop(tenant)
                self.evicted += 1
                continue
            break
        while len(self._states) > self.max_tenants:
            self._states.popitem(last=False)
            self.evicted += 1

    def try_admit(self, tenant: Optional[str], now: Optional[float] = None) -> Optional[float]:
        """Charge one request against ``tenant``'s buckets. ``None`` = admitted
        (the request bucket was debited); else the seconds until a retry could
        succeed — computed from the LIMITING bucket's actual refill rate, the
        value the 429's ``Retry-After`` carries. Anonymous requests are never
        limited. A failed admission leaves the buckets untouched (so a
        replica-walk retry is not double-charged) and bumps the tenant's shed
        counter."""
        if tenant is None:
            return None
        if now is None:
            now = self._clock()
        with self._lock:
            state = self._state_locked(tenant, now)
            state.refill(now)
            spec = state.spec
            if spec.req_per_s > 0 and state.req_tokens < 1.0:
                state.shed += 1
                return max((1.0 - state.req_tokens) / spec.req_per_s, 0.001)
            if spec.tokens_per_s > 0 and state.gen_tokens < 1.0:
                # generated-token debt: emissions post-charge this bucket, so
                # a long stream can overdraw — new admissions wait out the debt
                state.shed += 1
                return max((1.0 - state.gen_tokens) / spec.tokens_per_s, 0.001)
            if spec.req_per_s > 0:
                state.req_tokens -= 1.0
            state.admitted += 1
            return None

    def refund(self, tenant: Optional[str], now: Optional[float] = None) -> None:
        """Undo one :meth:`try_admit` charge: credit the request token back.

        For requests that were charged but never served — an exception between
        the successful admission and the stream actually entering the batch.
        Without the refund such failures silently erode the tenant's effective
        rate below its configured floor ("never double-charge, never charge on
        shed" — and never charge for work that was not done).  The credit is
        capped at the bucket's burst capacity, so a stray double refund cannot
        mint extra burst; a tenant evicted between charge and refund is a
        no-op (its bucket state is gone, and a fresh state starts full)."""
        if tenant is None:
            return
        if now is None:
            now = self._clock()
        with self._lock:
            state = self._states.get(tenant)
            if state is None:
                return
            state.last_seen = now
            if state.spec.req_per_s > 0:
                cap = max(state.spec.req_per_s * state.spec.burst_s, 1.0)
                state.req_tokens = min(cap, state.req_tokens + 1.0)
            state.refunded += 1

    def charge_tokens(self, tenant: Optional[str], n: int, now: Optional[float] = None) -> None:
        """Debit ``n`` generated tokens (called at engine emission sites).
        The bucket may go negative — debt that :meth:`try_admit` makes new
        admissions wait out — which is what makes a tokens/s limit meaningful
        for streams whose length is unknown at admission."""
        if tenant is None or n <= 0:
            return
        if now is None:
            now = self._clock()
        with self._lock:
            state = self._state_locked(tenant, now)
            state.generated_tokens += int(n)
            if state.spec.tokens_per_s > 0:
                state.refill(now)
                state.gen_tokens -= float(n)

    # ------------------------------------------------------------------ telemetry

    def stats(self) -> "Dict[str, Any]":
        """Bounded per-tenant counters for ``/metrics`` (the map itself is
        bounded at ``max_tenants``, so the label cardinality the Prometheus
        exposition mints is too)."""
        with self._lock:
            tenants = {
                tenant: {
                    "admitted": state.admitted,
                    "shed": state.shed,
                    "generated_tokens": state.generated_tokens,
                    "refunded": state.refunded,
                    "weight": state.spec.weight,
                }
                for tenant, state in self._states.items()
            }
            return {
                "count": len(tenants),
                "evicted": self.evicted,
                "max_tenants": self.max_tenants,
                "per_tenant": tenants,
            }


#: the process-wide registry, installed by the serving app (the same pattern
#: as observability.recorder's active recorder): engines built by app code
#: consult it at submit time without construction wiring. None = tenancy off.
_active: "Optional[TenantRegistry]" = None
_active_lock = threading.Lock()


def set_active_registry(registry: "Optional[TenantRegistry]") -> None:
    global _active
    with _active_lock:
        _active = registry


def active_registry() -> "Optional[TenantRegistry]":
    with _active_lock:
        return _active
