"""TPU model serving: HTTP app + dynamic micro-batching.

Parity surface: reference unionml/fastapi.py:15-70 (``serving_app`` registering
``POST /predict``, ``GET /health``, ``GET /`` and a startup hook that loads the model
from ``UNIONML_MODEL_PATH`` or the remote backend). FastAPI/uvicorn are not part of our
dependency set, so the server is a small stdlib-asyncio HTTP implementation — which
also gives us what FastAPI never could: a dynamic micro-batching queue between the
socket and the TPU so concurrent single-row requests ride one MXU dispatch.
"""

from unionml_tpu.serving.aot import AOTFunction, ProgramStore  # noqa: F401
from unionml_tpu.serving.app import ServingApp, serving_app  # noqa: F401
from unionml_tpu.serving.batcher import MicroBatcher, ServingConfig  # noqa: F401
from unionml_tpu.serving.cluster import (  # noqa: F401
    FleetCoordinator,
    LocalHost,
    RemoteHost,
    WorkerAgent,
    connect_fleet,
)
from unionml_tpu.serving.compile import CompiledPredictor  # noqa: F401
from unionml_tpu.serving.continuous import ContinuousBatcher  # noqa: F401
from unionml_tpu.serving.prefix_cache import RadixPrefixCache  # noqa: F401
from unionml_tpu.serving.replicas import ReplicaScheduler, ReplicaSet, slice_mesh  # noqa: F401
from unionml_tpu.serving.overload import (  # noqa: F401
    DeadlineExceeded,
    QueueFullError,
    TenantThrottled,
    current_deadline,
)
from unionml_tpu.serving.tenancy import (  # noqa: F401
    TenantRegistry,
    TenantSpec,
    current_priority,
    current_tenant,
)
