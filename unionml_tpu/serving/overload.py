"""Overload-protection primitives shared across the serving stack.

The north star is "heavy traffic from millions of users" — which means the
interesting regime is the one where demand exceeds capacity. Left alone, every
queue in the stack (the micro-batcher's asyncio.Queue, the continuous engine's
``_pending`` list, the socket backlog) grows without bound under overload and
every request eventually times out client-side after consuming server work —
congestion collapse. The fix ("The Tail at Scale", Dean & Barroso 2013) is to
bound admission and shed the excess *immediately*:

- :class:`QueueFullError` — an admission queue is at capacity; the HTTP layer
  maps it to ``429 Too Many Requests`` + ``Retry-After`` so well-behaved
  clients back off instead of retrying into the same wall.
- :class:`DeadlineExceeded` — the request's deadline passed while it was still
  queued (or mid-flight); mapped to ``503 Service Unavailable``. Work a client
  has already given up on must never reach the TPU.

Deadlines are absolute ``time.monotonic()`` instants. They enter at the HTTP
layer (``X-Request-Deadline-Ms`` header, clipped to the server's maximum, else
the server default) and propagate down through a :data:`request_deadline`
contextvar so handlers — and through them the micro-batcher and the continuous
engine — can shed expired work at every queue boundary without any signature
churn on the handler protocol.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional


class QueueFullError(Exception):
    """An admission queue is at capacity — shed now with 429 + ``Retry-After``."""

    def __init__(self, detail: str, retry_after_s: float = 1.0):
        super().__init__(detail)
        self.detail = detail
        self.retry_after_s = retry_after_s


class TenantThrottled(QueueFullError):
    """A per-tenant token bucket (serving/tenancy.py) is empty — shed with 429.

    A :class:`QueueFullError` subclass so every existing 429 path (HTTP
    mapping, ``Retry-After`` from ``retry_after_s``) applies unchanged, but
    distinguishable: the HTTP layer stamps ``shed_tenant_limit`` (not
    ``shed_queue_full``) on the metrics and the trace, and ``retry_after_s``
    is computed from the limiting bucket's ACTUAL refill time rather than the
    server's fixed hint — a well-behaved client backs off exactly as long as
    the limit requires, no longer. The replica scheduler re-raises it
    immediately instead of walking the fleet: every replica shares the same
    registry, so the walk could only re-shed."""

    def __init__(self, detail: str, retry_after_s: float = 1.0, tenant: Optional[str] = None):
        super().__init__(detail, retry_after_s=retry_after_s)
        self.tenant = tenant


class DeadlineExceeded(Exception):
    """The request's deadline passed before (or while) its work ran — shed with 503."""


#: absolute ``time.monotonic()`` deadline of the request currently being handled
#: (``None`` = no deadline). Set by ``HTTPServer`` around each handler call.
request_deadline: "contextvars.ContextVar[Optional[float]]" = contextvars.ContextVar(
    "request_deadline", default=None
)


def current_deadline() -> Optional[float]:
    """The active request's absolute deadline, if any (monotonic seconds)."""
    return request_deadline.get()


def remaining_s(deadline: Optional[float]) -> Optional[float]:
    """Seconds until ``deadline`` (may be negative); ``None`` when unbounded."""
    return None if deadline is None else deadline - time.monotonic()


def expired(deadline: Optional[float]) -> bool:
    return deadline is not None and time.monotonic() >= deadline
