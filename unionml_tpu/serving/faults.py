"""Deterministic fault injection for the serving fleet (docs/serving.md
"Fault tolerance").

The fleet's failure modes — a worker process dying, a control RPC dropped or
delayed by a congested DCN link, a token stream cut mid-flight — are exactly
the events a tier-1 CPU test cannot produce on demand by SIGKILLing
subprocesses at the right microsecond. This module makes them *schedulable*:
a :class:`FaultPlan` is a versioned, seeded list of events keyed on **virtual
time** (seconds since the plan was armed) and **host id**, and an
:class:`ArmedFaultPlan` is the live injector the cluster layer consults at
its transport boundaries (``RemoteHost._call`` / ``_stream_call`` on the
coordinator side, the ``WorkerAgent`` control handler on the worker side).
Every fault a plan fires is reproducible: the same plan against the same
fleet produces the same drops at the same virtual instants, so the lifecycle
state machine (suspect → dead → probation → live), the zero-token stream
retry, and coordinator failover are all pinned by ordinary deterministic
tests — and the ``fleet_chaos`` bench lane replays a recorded traffic mix
while the plan kills and restores a worker.

Event kinds (all windowed — an event is active for ``for_s`` seconds from
its ``t``):

- ``worker_kill`` — the host is unreachable for the window: coordinator-side
  RPCs to it raise :class:`FaultInjected` (a ``ConnectionError``, so the
  lifecycle machinery treats it exactly like a real dead worker);
  worker-side, the control handler drops the connection without answering.
- ``rpc_drop`` — individual control RPCs in the window fail with
  :class:`FaultInjected` (probability ``p`` per call, drawn from the plan's
  seeded RNG — ``p=1.0`` drops every call, deterministically).
- ``rpc_delay`` — RPCs in the window sleep ``delay_s`` before proceeding
  (the slow-scrape case that must cost a retry, not a host).
- ``stream_cut`` — a token stream *started* in the window is severed after
  ``after_tokens`` chunks (0 = before the first token, the retryable case).

Plans are armed via ``serve --fault-plan`` / ``UNIONML_TPU_FAULT_PLAN``
(a path to a plan JSON, or the JSON inline) with the same early-export
contract as every serve knob, or programmatically
(``FleetCoordinator.arm_faults`` / ``WorkerAgent(fault_plan=...)``).
"""

from __future__ import annotations

import json
import random
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from unionml_tpu._logging import logger
from unionml_tpu.defaults import serve_fault_plan

__all__ = [
    "ArmedFaultPlan",
    "FAULT_KINDS",
    "FaultEvent",
    "FaultInjected",
    "FaultPlan",
    "PLAN_VERSION",
    "default_chaos_plan",
]

#: plan schema version: a reader rejects plans from a future schema instead
#: of silently misreading them
PLAN_VERSION = 1

FAULT_KINDS = ("worker_kill", "rpc_drop", "rpc_delay", "stream_cut")


class FaultInjected(ConnectionError):
    """An injected transport failure. A ``ConnectionError`` subclass so every
    existing dead-host path (``_DEAD_ERRORS`` in serving/cluster.py) treats
    it exactly like the real thing — the point of injection is that the
    production machinery cannot tell the difference."""


class FaultEvent:
    """One scheduled fault: ``kind`` at virtual second ``t`` for ``for_s``
    seconds, scoped to ``host`` (``None`` = every host)."""

    __slots__ = ("t", "kind", "host", "for_s", "delay_s", "after_tokens", "p")

    def __init__(
        self,
        t: float,
        kind: str,
        *,
        host: Optional[int] = None,
        for_s: Optional[float] = None,
        delay_s: float = 0.05,
        after_tokens: int = 0,
        p: float = 1.0,
    ):
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; expected one of {FAULT_KINDS}")
        if t < 0:
            raise ValueError(f"fault time must be >= 0 (got {t})")
        if for_s is None:
            for_s = 1.0 if kind == "worker_kill" else 0.25
        if for_s <= 0:
            raise ValueError(f"fault window for_s must be > 0 (got {for_s})")
        if not (0.0 < p <= 1.0):
            raise ValueError(f"fault probability p must be in (0, 1] (got {p})")
        if delay_s < 0 or after_tokens < 0:
            raise ValueError("delay_s and after_tokens must be >= 0")
        self.t = float(t)
        self.kind = kind
        self.host = None if host is None else int(host)
        self.for_s = float(for_s)
        self.delay_s = float(delay_s)
        self.after_tokens = int(after_tokens)
        self.p = float(p)

    def matches(self, host_id: Optional[int]) -> bool:
        return self.host is None or host_id is None or self.host == int(host_id)

    def active_at(self, vnow: float) -> bool:
        return self.t <= vnow < self.t + self.for_s

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"t": self.t, "kind": self.kind, "for_s": self.for_s}
        if self.host is not None:
            out["host"] = self.host
        if self.kind == "rpc_delay":
            out["delay_s"] = self.delay_s
        if self.kind == "stream_cut":
            out["after_tokens"] = self.after_tokens
        if self.p != 1.0:
            out["p"] = self.p
        return out


class FaultPlan:
    """An ordered, seeded schedule of :class:`FaultEvent` s.

    Pure data: parsing and serialization are canonical (sorted events,
    version stamped), and every probabilistic choice an armed plan makes
    rides one ``random.Random(seed)`` — the same plan is the same chaos,
    byte for byte and drop for drop."""

    def __init__(self, events: "Sequence[FaultEvent]", *, seed: int = 0, version: int = PLAN_VERSION):
        if int(version) != PLAN_VERSION:
            raise ValueError(
                f"unsupported fault-plan version {version} (this build reads {PLAN_VERSION})"
            )
        self.version = PLAN_VERSION
        self.seed = int(seed)
        self.events: "List[FaultEvent]" = sorted(
            events, key=lambda e: (e.t, e.kind, -1 if e.host is None else e.host)
        )

    @classmethod
    def parse(cls, spec: "str | Dict[str, Any]") -> "FaultPlan":
        """Build a plan from its JSON text or already-parsed dict; raises
        ``ValueError`` on schema violations (the CLI surfaces it as a usage
        error; the env reader degrades instead)."""
        if isinstance(spec, str):
            try:
                spec = json.loads(spec)
            except ValueError as exc:
                raise ValueError(f"fault plan is not valid JSON: {exc}")
        if not isinstance(spec, dict):
            raise ValueError("a fault plan must be a JSON object with an 'events' list")
        raw_events = spec.get("events")
        if not isinstance(raw_events, list):
            raise ValueError("a fault plan must carry an 'events' list")
        events = []
        for entry in raw_events:
            if not isinstance(entry, dict) or "t" not in entry or "kind" not in entry:
                raise ValueError(f"bad fault event {entry!r}: needs at least 't' and 'kind'")
            kwargs = {
                key: entry[key]
                for key in ("host", "for_s", "delay_s", "after_tokens", "p")
                if entry.get(key) is not None
            }
            events.append(FaultEvent(float(entry["t"]), str(entry["kind"]), **kwargs))
        return cls(events, seed=int(spec.get("seed", 0)), version=int(spec.get("version", PLAN_VERSION)))

    @classmethod
    def load(cls, path: "str | Path") -> "FaultPlan":
        return cls.parse(Path(path).read_text())

    @classmethod
    def from_env(cls) -> "Optional[FaultPlan]":
        """The plan named by ``UNIONML_TPU_FAULT_PLAN`` (a path, or inline
        JSON starting with ``{``); ``None`` when unset. A garbage value warns
        and degrades to no plan — a typo'd chaos knob must never take a
        production serve down (the serve-export contract)."""
        raw = serve_fault_plan()
        if raw is None:
            return None
        try:
            if raw.lstrip().startswith("{"):
                return cls.parse(raw)
            return cls.load(raw)
        except (OSError, ValueError) as exc:
            logger.warning(f"ignoring UNIONML_TPU_FAULT_PLAN ({exc}); serving without fault injection")
            return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "seed": self.seed,
            "events": [event.to_dict() for event in self.events],
        }

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, separators=(",", ":"))

    @property
    def horizon_s(self) -> float:
        """Virtual second the last event window closes (0.0 for an empty
        plan) — the chaos lane uses it to size the replay."""
        return max((event.t + event.for_s for event in self.events), default=0.0)

    def fault_times(self) -> "List[float]":
        """Onset instants of the disruptive events (worker_kill/rpc_drop/
        stream_cut) — the recovery-accounting inputs for
        :func:`unionml_tpu.workloads.verdicts.availability`."""
        return sorted({e.t for e in self.events if e.kind != "rpc_delay"})

    def arm(self, *, clock: Any = time.monotonic) -> "ArmedFaultPlan":
        return ArmedFaultPlan(self, clock=clock)


class ArmedFaultPlan:
    """A :class:`FaultPlan` bound to a start instant — the live injector.

    One armed plan may be shared by every coordinator-side host handle AND a
    worker agent: virtual time is common, and the injection counters
    aggregate. All methods are thread-safe; the fast path (no event active)
    is a couple of float compares."""

    def __init__(self, plan: FaultPlan, *, clock: Any = time.monotonic):
        self.plan = plan
        self._clock = clock
        self._t0 = float(clock())
        self._rng = random.Random(plan.seed)
        self._lock = threading.Lock()
        self._injected: Dict[str, int] = {kind: 0 for kind in FAULT_KINDS}

    def elapsed_s(self) -> float:
        return float(self._clock()) - self._t0

    def _active(self, kind: str, host_id: Optional[int]) -> "Optional[FaultEvent]":
        vnow = self.elapsed_s()
        for event in self.plan.events:
            if event.kind == kind and event.active_at(vnow) and event.matches(host_id):
                return event
        return None

    def _fires(self, event: FaultEvent) -> bool:
        if event.p >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < event.p

    def _count(self, kind: str) -> None:
        with self._lock:
            self._injected[kind] += 1

    def worker_down(self, host_id: Optional[int]) -> bool:
        """Whether a ``worker_kill`` window currently covers ``host_id``."""
        event = self._active("worker_kill", host_id)
        if event is None:
            return False
        self._count("worker_kill")
        return True

    def check_rpc(self, host_id: Optional[int], what: str = "rpc") -> None:
        """Consult the plan before a control RPC to ``host_id``: raises
        :class:`FaultInjected` for ``worker_kill``/``rpc_drop`` windows,
        sleeps through an ``rpc_delay`` window, and is a no-op otherwise."""
        if self.worker_down(host_id):
            raise FaultInjected(
                f"fault-injected worker_kill: host {host_id} is down ({what})"
            )
        event = self._active("rpc_drop", host_id)
        if event is not None and self._fires(event):
            self._count("rpc_drop")
            raise FaultInjected(f"fault-injected rpc_drop: {what} to host {host_id}")
        event = self._active("rpc_delay", host_id)
        if event is not None and self._fires(event):
            self._count("rpc_delay")
            time.sleep(event.delay_s)

    def stream_cut_after(self, host_id: Optional[int]) -> Optional[int]:
        """Chunk count after which a stream STARTED now should be severed
        (``None`` = no cut scheduled)."""
        event = self._active("stream_cut", host_id)
        if event is None or not self._fires(event):
            return None
        self._count("stream_cut")
        return event.after_tokens

    def stats(self) -> Dict[str, int]:
        """Injection counters (ints only — the /metrics no-None contract)."""
        with self._lock:
            out = dict(self._injected)
        out["events"] = len(self.plan.events)
        return out


def default_chaos_plan(
    seed: int = 0, *, host: int = 1, kill_at_s: float = 0.75, down_s: float = 1.0
) -> FaultPlan:
    """The kill-and-rejoin plan the ``fleet_chaos`` bench lane (and the
    ``chaos_fleet`` scenario docs) pair with a recorded mix: drop a few
    control RPCs to warm the suspect path, then take the host down for
    ``down_s`` — recovery is the lifecycle machine's job, and the replay's
    availability verdict is the judge."""
    return FaultPlan(
        [
            FaultEvent(max(kill_at_s - 0.3, 0.0), "rpc_drop", host=host, for_s=0.2),
            FaultEvent(kill_at_s, "worker_kill", host=host, for_s=down_s),
        ],
        seed=seed,
    )
