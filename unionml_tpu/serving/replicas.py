"""Data-parallel replica serving: N continuous engines behind one scheduler.

The continuous engine (:mod:`unionml_tpu.serving.continuous`) shards over
model/TP axes only — a ``[1, ...]`` admission row cannot split a batch axis, so
a mesh with ``data``/``fsdp`` > 1 used to be rejected outright and multi-chip
serving was TP-only. At fleet scale the first knob an operator reaches for is
the other one: *replicas*. Orca (OSDI '22) and vLLM (SOSP '23) both assume the
iteration-level scheduler sits above a pool of replicated engines; this module
is that layer.

Design:

- :func:`slice_mesh` cuts the device mesh along its batch axes (``dcn_data``,
  ``data``, ``fsdp``) into per-replica TP submeshes — each keeps the full axis
  set with batch axes at 1, so every Generator code path (TP collectives,
  sequence-parallel prefill, paged pools) runs unchanged inside a replica;
- :class:`ReplicaSet` builds one Generator + :class:`ContinuousBatcher` per
  submesh (params re-placed per slice; within a replica the batch axes are 1,
  so placement replicates) and owns their shared lifecycle (warmup in
  parallel, drain on close);
- :class:`ReplicaScheduler` admits requests least-loaded-first — load is a
  replica's live residents plus live waiters PLUS its pending prefill
  backlog in tokens (``ContinuousBatcher.load()``'s token weighting), so two
  replicas with equal waiter counts but a 10k-token vs a 10-token queued
  prompt do not tie — with prefix-affinity routing so shared-prefix
  requests land on the replica whose KV pool already holds that prefix. With
  per-engine radix prefix caches on (``prefix_cache=True``), affinity routes
  on each replica's ACTUAL cached-prefix length for the prompt (the radix
  probe ``cached_prefix_tokens``) — the scheduler is the cross-replica tier
  of the same cache; without them the bounded-LRU token-key heuristic
  (``affinity_tokens``) remains the fallback. The affinity margin check and
  the hotspot fallback rank on the SAME token-weighted loads, so a fallback
  never lands on a replica with a deep prefill backlog that mere waiter
  counts would hide.

- **disaggregation** (DistServe's prefill/decode split, docs/serving.md
  "Disaggregated and elastic serving"): replicas may carry a role —
  ``prefill``, ``decode``, or ``mixed`` (the default, today's behavior) via
  ``roles=``/``serve --replica-roles``. Prompts above ``prefill_threshold``
  tokens admit on a prefill replica with the engine's ``export_handoff`` and
  their finished KV row hands off to a decode replica
  (:meth:`ContinuousBatcher.import_handoff`) — token-identical to a mixed
  replica, but resident decode streams never stall behind the prefill; warm
  multi-turn prompts whose radix-cached run on a decode replica already
  covers most of the prompt admit there directly (the shortcut);
- **elasticity**: :meth:`ReplicaSet.scale_to` grows the fleet onto spare
  submeshes (params re-placed, engine warmed BEFORE joining the scheduler)
  or drains the tail replica with zero in-flight loss (quiesce → drain →
  close, PR 1's machinery per replica), and an optional watermark autoscaler
  rides the windowed load/health signal (PR 8) to do it automatically.

Overload posture composes with PR 1's machinery: an expired deadline sheds
before routing (:class:`DeadlineExceeded`, HTTP 503), and a prompt is shed
with :class:`QueueFullError` (HTTP 429) only when EVERY replica's bounded
waiting queue is full — the scheduler walks replicas in load order, so a
single hot replica never turns away work the rest of the fleet could take.

``ContinuousBatcher(generator, ...)`` with a dp>1 mesh (or with the serve
CLI's ``--dp-replicas`` exported) transparently constructs a ReplicaSet —
existing apps opt into replica serving by mesh shape or CLI flag, with no code
changes; the set mirrors the engine's public surface (``submit`` / ``warmup``
/ ``stats`` / ``close``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.defaults import (
    REPLICA_ROLES,
    serve_autoscale_high,
    serve_autoscale_interval_s,
    serve_autoscale_low,
    serve_dp_replicas,
    serve_max_replicas,
    serve_min_replicas,
    serve_prefill_threshold,
    serve_replica_roles,
)
from unionml_tpu.observability.trace import current_trace
from unionml_tpu.parallel.mesh import BATCH_AXES
from unionml_tpu.serving.continuous import ContinuousBatcher
from unionml_tpu.serving.overload import (
    DeadlineExceeded,
    QueueFullError,
    TenantThrottled,
    expired,
)
from unionml_tpu.serving.tenancy import current_tenant

__all__ = ["ReplicaScheduler", "ReplicaSet", "dp_extent", "slice_mesh"]


def dp_extent(mesh: Any) -> int:
    """Product of a mesh's batch (data-parallel) axis sizes — the natural
    replica count of :func:`slice_mesh`. 1 for ``None`` or a TP-only mesh."""
    if mesh is None:
        return 1
    extent = 1
    for axis in BATCH_AXES:
        extent *= int(mesh.shape.get(axis, 1))
    return extent


def slice_mesh(mesh: Any, replicas: Optional[int] = None) -> "List[Any]":
    """Slice a device mesh along its batch axes into per-replica TP submeshes.

    Each submesh keeps the mesh's full axis-name set with every batch axis at
    size 1 (``sequence``/``expert``/``pipe`` extents unchanged), so a
    Generator built over it behaves exactly like a TP-only engine. With
    ``replicas`` equal to the batch-axis product (the default), each replica
    owns exactly one batch slice. A SMALLER ``replicas`` that **divides** the
    product builds a hybrid mesh per replica (the T5X device-regrouping
    shape): the leftover batch extent folds into the ``model`` axis, so 2
    replicas over a dp=4×tp=2 mesh each serve tp=4 — fewer, fatter replicas
    from the same chips. Any other count raises a :class:`ValueError` naming
    the batch-axis extents (historically this surfaced as an opaque reshape
    error deep in mesh construction).
    """
    from jax.sharding import Mesh

    names = tuple(mesh.axis_names)
    devices = np.asarray(mesh.devices)
    batch_dims = [i for i, n in enumerate(names) if n in BATCH_AXES and devices.shape[i] > 1]
    total = int(np.prod([devices.shape[i] for i in batch_dims])) if batch_dims else 1
    if replicas is None:
        replicas = total
    extents = ", ".join(
        f"{names[i]}={devices.shape[i]}" for i in batch_dims
    ) or "none > 1"
    if replicas < 1 or total % replicas:
        raise ValueError(
            f"replicas ({replicas}) must divide the mesh's data-parallel extent ({total}; "
            f"batch axes: {extents}) — each replica owns a whole number of batch slices, "
            "with any leftover extent folded into the model axis"
        )
    if total == 1:
        return [mesh]
    group = total // replicas
    if group > 1 and "model" not in names:
        raise ValueError(
            f"cannot group {group} batch slices per replica: the mesh has no 'model' "
            f"axis to fold the leftover extent (batch axes: {extents}) into"
        )
    batch_shape = tuple(devices.shape[i] for i in batch_dims)
    # batch axes to the front, flattened: grouped[g] is one batch slice's devices
    grouped = np.moveaxis(devices, batch_dims, range(len(batch_dims))).reshape(
        (total,) + tuple(
            devices.shape[i] for i in range(devices.ndim) if i not in batch_dims
        )
    )
    rest_names = [names[i] for i in range(devices.ndim) if i not in batch_dims]
    out = []
    for r in range(replicas):
        sub = grouped[r * group : (r + 1) * group]
        if group > 1:
            # fold the grouped batch extent into the model axis: move the
            # group dim to just before model, then merge the two
            m = rest_names.index("model")
            sub = np.moveaxis(sub, 0, m)
            shape = list(sub.shape)
            shape[m : m + 2] = [shape[m] * shape[m + 1]]
            sub = sub.reshape(shape)
        else:
            sub = sub[0]
        # re-expand to the full axis-name set with batch axes at size 1 (the
        # remaining dims keep their relative order, so inserting 1s is exact)
        final = [1] * len(names)
        for i, name in enumerate(names):
            if i not in batch_dims:
                final[i] = sub.shape[rest_names.index(name)]
        out.append(Mesh(sub.reshape(final), names))
    return out


class ReplicaScheduler:
    """Least-loaded-first routing over N replicas, with optional prefix affinity.

    Load is supplied by the caller per decision (the engine's token-weighted
    ``load()``: live residents + live waiters + prefill backlog tokens
    normalized by the admission chunk — ints or floats both rank); ties break
    toward the lowest index, so an idle fleet fills in order and drains
    evenly. Both the affinity-margin comparison and the hotspot-fallback
    ranking use these same loads, so mixed prompt lengths route sensibly on
    every path. ``affinity_tokens > 0`` enables prefix-affinity
    routing: requests sharing their first ``affinity_tokens`` prompt tokens are
    steered to the replica that last served that prefix — its KV pool already
    holds those rows/pages (shared-prefix pages in paged mode), so the prefill
    is warm — unless that replica is more than ``affinity_margin`` requests
    busier than the least-loaded one. The margin keeps a popular prefix from
    turning one replica into a hotspot while the rest idle; the affinity map is
    a bounded LRU, so unbounded prefix cardinality cannot grow host memory.
    """

    def __init__(
        self,
        replicas: int,
        *,
        affinity_tokens: int = 0,
        affinity_margin: int = 2,
        affinity_capacity: int = 4096,
        tenant_affinity_capacity: int = 1024,
    ):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if affinity_tokens < 0 or affinity_margin < 0 or affinity_capacity < 1:
            raise ValueError("affinity knobs must be non-negative (capacity >= 1)")
        if tenant_affinity_capacity < 1:
            raise ValueError("tenant_affinity_capacity must be >= 1")
        self.replicas = replicas
        self.affinity_tokens = affinity_tokens
        self.affinity_margin = affinity_margin
        self._affinity_capacity = affinity_capacity
        self._affinity: "OrderedDict[Tuple[int, ...], int]" = OrderedDict()
        #: TENANT session affinity (ROADMAP 4(b)): tenant id -> the replica
        #: that last served it. A tenant's recent sessions left their KV in
        #: that replica's radix tier, so landing its next request there is a
        #: warm prefill even when the new prompt shares no prefix the radix
        #: PROBE can see yet (a fresh conversation). Bounded LRU — the TPU009
        #: discipline — and margin-gated exactly like prefix affinity, so a
        #: single heavy tenant cannot hotspot one replica while siblings idle.
        self._tenant_affinity_capacity = tenant_affinity_capacity
        self._tenant_affinity: "OrderedDict[str, int]" = OrderedDict()
        self._lock = threading.Lock()
        #: routing telemetry: successful submissions per replica, and how many
        #: rode the affinity maps vs plain least-loaded
        self.submitted = [0] * replicas
        self.affinity_hits = 0
        self.tenant_affinity_hits = 0

    def _key(self, prompt: Optional[Sequence[int]]) -> Optional[Tuple[int, ...]]:
        if not self.affinity_tokens or prompt is None:
            return None
        if len(prompt) < self.affinity_tokens:
            return None  # shorter than the affinity window: nothing shared to exploit
        return tuple(int(t) for t in prompt[: self.affinity_tokens])

    def resize(self, replicas: int) -> None:
        """Track an elastic fleet resize: per-replica telemetry follows the
        index alignment (the replica layer adds/removes at the TAIL, so kept
        indexes keep their counts); affinity entries pointing past the new
        count are dropped — their replica is gone."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        with self._lock:
            if replicas > len(self.submitted):
                self.submitted.extend([0] * (replicas - len(self.submitted)))
            else:
                del self.submitted[replicas:]
                self._affinity = OrderedDict(
                    (key, idx) for key, idx in self._affinity.items() if idx < replicas
                )
                self._tenant_affinity = OrderedDict(
                    (t, idx) for t, idx in self._tenant_affinity.items() if idx < replicas
                )
            self.replicas = replicas

    def order(
        self,
        loads: Sequence[int],
        prompt: Optional[Sequence[int]] = None,
        cached: Optional[Sequence[int]] = None,
        breaching: Optional[Sequence[bool]] = None,
        deprioritized: Optional[Sequence[bool]] = None,
        tenant: Optional[str] = None,
    ) -> "Tuple[List[int], Any]":
        """``(indices to try best-first, head_is_affinity)``. The caller walks
        the list so a full (QueueFullError) replica falls through to the
        next-least-loaded instead of shedding work the rest of the fleet could
        take; the flag marks whether the head came from affinity routing (for
        hit accounting) rather than pure load order.

        ``cached`` — per-replica ACTUAL cached-prefix token counts (each
        engine's ``cached_prefix_tokens(prompt)`` radix probe) — takes
        precedence over the token-key LRU heuristic: the replica whose KV pool
        already holds the longest run of this prompt is preferred, unless it
        is more than ``affinity_margin`` load units busier than the least
        loaded (the same hotspot guard). The LRU map remains the fallback for
        engines without a prefix cache.

        ``breaching`` — per-replica SLO-breach flags (each engine's
        ``health()["state"] == "breach"``, the observability→routing feedback)
        — deprioritizes a breaching replica below EVERY non-breaching one
        regardless of load, and disqualifies it from heading the order via
        affinity: sending a warm-prefix request to a replica that is already
        missing its latency targets would trade a prefill for a breach. A
        breaching replica still appears in the walk order, so a fleet that is
        breaching everywhere degrades to plain least-loaded rather than
        shedding.

        ``deprioritized`` — per-replica role-mismatch flags from the
        disaggregated fleet (a prefill-role replica should not take
        decode-resident work unless everyone suited is full) — merges with
        ``breaching``: flagged replicas sort below every unflagged one but
        stay in the walk order, the same degrade-don't-shed posture.

        ``tenant`` — the submitting tenant id — arms TENANT session affinity
        as the LAST fallback: when neither an actual radix probe nor the
        prefix-key map produced a warm head, the replica that last served
        this tenant is preferred under the same margin gate (its radix tier
        holds the tenant's recent sessions' KV — the multi-turn-chat warmth a
        prefix probe on a brand-new prompt cannot see). A tenant-affinity
        head is flagged ``"tenant"`` (truthy, distinct from the prefix
        paths' ``True``) so :meth:`note` can account it separately."""
        avoid = (
            [bool(flag) for flag in breaching]
            if breaching is not None and len(breaching) == len(loads)
            else [False] * len(loads)
        )
        if deprioritized is not None and len(deprioritized) == len(loads):
            avoid = [a or bool(d) for a, d in zip(avoid, deprioritized)]
        ranked = sorted(range(len(loads)), key=lambda i: (avoid[i], loads[i], i))
        if cached is not None and len(cached) == len(loads) and max(cached, default=0) > 0:
            # warm replicas that are NOT breaching compete on cached length; a
            # breaching replica's warm cache never heads the order
            candidates = [i for i in range(len(loads)) if cached[i] > 0 and not avoid[i]]
            if candidates:
                preferred = min(candidates, key=lambda i: (-cached[i], loads[i], i))
                if loads[preferred] <= loads[ranked[0]] + self.affinity_margin:
                    return [preferred] + [i for i in ranked if i != preferred], True
            return self._tenant_head(ranked, loads, avoid, tenant)
        key = self._key(prompt)
        if key is not None:
            with self._lock:
                preferred = self._affinity.get(key)
            if (
                preferred is not None
                and not avoid[preferred]
                and loads[preferred] <= loads[ranked[0]] + self.affinity_margin
            ):
                return [preferred] + [i for i in ranked if i != preferred], True
        return self._tenant_head(ranked, loads, avoid, tenant)

    def _tenant_head(
        self,
        ranked: "List[int]",
        loads: Sequence[int],
        avoid: "List[bool]",
        tenant: Optional[str],
    ) -> "Tuple[List[int], Any]":
        """The tenant-session-affinity fallback head (see :meth:`order`)."""
        if tenant is None or not ranked:
            return ranked, False
        with self._lock:
            preferred = self._tenant_affinity.get(tenant)
        if (
            preferred is not None
            and preferred < len(loads)
            and not avoid[preferred]
            and loads[preferred] <= loads[ranked[0]] + self.affinity_margin
        ):
            return [preferred] + [i for i in ranked if i != preferred], "tenant"
        return ranked, False

    def note(
        self,
        replica: int,
        prompt: Optional[Sequence[int]] = None,
        *,
        affinity: Any = False,
        tenant: Optional[str] = None,
    ) -> None:
        """Record a successful routing decision (updates the affinity maps).
        ``affinity`` is the head flag :meth:`order` returned when this replica
        was its head — ``True`` counts a prefix/probe hit, ``"tenant"`` a
        tenant-session hit."""
        key = self._key(prompt)
        with self._lock:
            if replica >= len(self.submitted):
                # a routing snapshot can outlive a concurrent resize by a few
                # microseconds; re-grow rather than drop the count
                self.submitted.extend([0] * (replica + 1 - len(self.submitted)))
            self.submitted[replica] += 1
            if affinity == "tenant":
                self.tenant_affinity_hits += 1
            elif affinity:
                self.affinity_hits += 1
            if key is not None:
                self._affinity[key] = replica
                self._affinity.move_to_end(key)
                while len(self._affinity) > self._affinity_capacity:
                    self._affinity.popitem(last=False)
            if tenant is not None:
                self._tenant_affinity[tenant] = replica
                self._tenant_affinity.move_to_end(tenant)
                while len(self._tenant_affinity) > self._tenant_affinity_capacity:
                    self._tenant_affinity.popitem(last=False)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "policy": "least-loaded",
                "submitted": list(self.submitted),
                "affinity_tokens": self.affinity_tokens,
                "affinity_hits": self.affinity_hits,
                "affinity_entries": len(self._affinity),
                "tenant_affinity_hits": self.tenant_affinity_hits,
                "tenant_affinity_entries": len(self._tenant_affinity),
            }


class ReplicaSet:
    """N data-parallel :class:`ContinuousBatcher` replicas behind one scheduler.

    >>> rs = ReplicaSet.build(module, params, gen_config,
    ...                       mesh=MeshSpec(data=2, model=2).build(),
    ...                       partition_rules=llama_partition_rules(),
    ...                       slots=4, decode_chunk=8)
    >>> for chunk in rs.submit([1, 5, 9]):
    ...     ...
    >>> rs.close()

    The public surface mirrors the single engine (``submit`` / ``warmup`` /
    ``stats`` / ``close``), so everything that composes with a
    ``ContinuousBatcher`` — the stream-predictor route, ``/metrics``, graceful
    drain — composes with a replica set unchanged. Engine knobs (``slots``,
    ``decode_chunk``, ``block_size``, ``pool_blocks``, ``max_waiting``,
    ``admit_chunk``/``prefill_budget``/``max_admissions`` — stall-free
    admission — ``prefix_cache`` — the radix prefix cache, see
    serving/continuous.py — ``slo`` — the fleet health & SLO engine —
    and ``prefix``) apply PER REPLICA; a shared ``prefix`` (token ids or a
    ``PrefixCache`` built with ``cache_prefix``) is prefilled once per replica
    at construction, since cache rows cannot cross submeshes.

    ``roles`` (``{"prefill": 1, "decode": 3}``, a per-replica list, or the
    ``serve --replica-roles`` export) splits the fleet into a prefill tier and
    a decode tier with KV handoff between them; ``prefill_threshold`` sets
    the prompt length that takes the disaggregated path; ``autoscale`` (a
    watermark dict, ``None`` = the ``UNIONML_TPU_AUTOSCALE_*`` exports,
    ``False`` = off) arms the elastic-resize loop around :meth:`scale_to`.
    All three default to today's symmetric, fixed fleet.
    """

    def __init__(
        self,
        generators: Optional[Sequence[Any]] = None,
        *,
        engines: Optional[Sequence[Any]] = None,
        slots: int = 4,
        decode_chunk: int = 8,
        prefix: Optional[Any] = None,
        block_size: Optional[int] = None,
        pool_blocks: Optional[int] = None,
        max_waiting: Optional[int] = None,
        admit_chunk: Optional[int] = None,
        prefill_budget: Optional[int] = None,
        max_admissions: Optional[int] = None,
        affinity_tokens: int = 0,
        affinity_margin: int = 2,
        trace: Optional[bool] = None,
        prefix_cache: Optional[bool] = None,
        slo: Optional[Any] = None,
        roles: Optional[Any] = None,
        prefill_threshold: Optional[int] = None,
        autoscale: Optional[Any] = None,
        tenancy: Optional[Any] = None,
        aot: Optional[Any] = None,
    ):
        if (generators is None) == (engines is None):
            raise ValueError("pass exactly one of generators= or engines=")
        prefix_tokens = self._prefix_tokens(prefix) if generators is not None else None
        count = len(list(engines)) if engines is not None else len(list(generators))
        self._roles = self._resolve_roles(roles, count)
        has_roles = any(r != "mixed" for r in self._roles)
        #: engine knobs retained for elastic scale-up (a new replica must be
        #: built exactly like its siblings — the KV-handoff width contract)
        self._engine_kwargs = dict(
            slots=slots, decode_chunk=decode_chunk, block_size=block_size,
            pool_blocks=pool_blocks, max_waiting=max_waiting, admit_chunk=admit_chunk,
            prefill_budget=prefill_budget, max_admissions=max_admissions,
            trace=trace, prefix_cache=prefix_cache, slo=slo, tenancy=tenancy,
            aot=aot,
        )
        self._prefix_tokens_saved = prefix_tokens
        if engines is not None:
            self._batchers: "List[Any]" = list(engines)
            if has_roles:
                for batcher, role in zip(self._batchers, self._roles):
                    batcher.role = role
        else:
            self._batchers = []
            try:
                for gen, role in zip(generators, self._roles):
                    self._batchers.append(
                        self._new_engine(gen, role if has_roles else None)
                    )
            except BaseException:
                for batcher in self._batchers:
                    batcher.close(wait=False)
                raise
        if not self._batchers:
            raise ValueError("a ReplicaSet needs at least one replica")
        self._scheduler = ReplicaScheduler(
            len(self._batchers), affinity_tokens=affinity_tokens, affinity_margin=affinity_margin
        )
        self._lock = threading.Lock()
        #: serializes resizes (scale_to callers + the autoscaler thread); the
        #: plain lock above stays counter/snapshot-granular so routing never
        #: waits behind a multi-second drain
        self._scale_lock = threading.Lock()
        #: prompt-length threshold for the disaggregated path: admissions at
        #: least this long route to a prefill-role replica and hand their KV
        #: off to a decode replica (0 = every admission, once roles exist)
        if prefill_threshold is None:
            prefill_threshold = serve_prefill_threshold()
        if prefill_threshold < 0:
            raise ValueError("prefill_threshold must be >= 0")
        self._prefill_threshold = int(prefill_threshold)
        #: per-replica mesh each engine was placed on (None when unknown —
        #: e.g. hand-built engines); scale-down returns it to the spare pool
        self._replica_meshes: "List[Any]" = [None] * len(self._batchers)
        #: construction template for scale-up (set by build()/from_generator;
        #: None = scale_to can only shrink)
        self._scale_template: "Optional[Dict[str, Any]]" = None
        #: fleet-level sheds: a deadline that expired before routing, and
        #: prompts turned away because EVERY replica's bounded queue was full
        #: (per-replica counters additionally record each engine's own sheds)
        self.shed_deadline = 0
        self.shed_queue_full = 0
        #: routing decisions that walked past an SLO-breaching replica that
        #: pure load order would have picked (the observability→routing
        #: feedback loop, made observable itself)
        self.breach_avoided = 0
        #: disaggregated-routing telemetry: admissions sent down the
        #: prefill→decode handoff path, and warm multi-turn prompts admitted
        #: directly on the decode replica whose radix cache already held them
        self.handoff_routes = 0
        self.handoff_shortcuts = 0
        #: elastic-resize telemetry
        self.scaled_up = 0
        self.scaled_down = 0
        # ---- autoscaler (env-armed by default, the --slo-* contract):
        # None reads the UNIONML_TPU_AUTOSCALE_* exports, a dict overrides
        # them, False disables the loop entirely
        self._autoscale: "Optional[Dict[str, Any]]" = None
        self._autoscale_stop = threading.Event()
        self._autoscale_thread: Optional[threading.Thread] = None
        if autoscale is None:
            high = serve_autoscale_high()
            if high > 0:
                self.configure_autoscaler(
                    high=high,
                    low=serve_autoscale_low(),
                    interval_s=serve_autoscale_interval_s(),
                    min_replicas=serve_min_replicas(),
                    max_replicas=serve_max_replicas(),
                )
        elif autoscale is not False:
            if not isinstance(autoscale, dict):
                raise TypeError(
                    f"autoscale must be a dict of watermarks, None (read the "
                    f"UNIONML_TPU_AUTOSCALE_* exports) or False, got {type(autoscale).__name__}"
                )
            self.configure_autoscaler(**autoscale)

    @staticmethod
    def _prefix_tokens(prefix: Optional[Any]) -> "Optional[List[int]]":
        if prefix is None:
            return None
        tokens = getattr(prefix, "tokens", prefix)  # PrefixCache or raw ids
        if tokens is None:
            raise ValueError(
                "a shared prefix for a ReplicaSet needs its token ids (build it with "
                "cache_prefix(...) or pass the ids directly); hand-built PrefixCaches "
                "cannot be re-prefilled per replica"
            )
        return [int(t) for t in tokens]

    @staticmethod
    def _resolve_roles(roles: Optional[Any], count: int) -> "List[str]":
        """Per-replica role list from a ``{role: count}`` dict, an explicit
        per-replica list, or (``None``) the ``serve --replica-roles`` export.
        Explicit specs that do not sum to the fleet size raise; the
        env-derived spec warns and falls back to an all-mixed fleet (the
        warn-and-degrade contract every serve export follows). Expansion
        order is prefill, then decode, then mixed — so scale-down (which
        drains the TAIL) sheds capacity replicas before the prefill tier."""
        strict = roles is not None
        if roles is None:
            roles = serve_replica_roles() or None
        if roles is None:
            return ["mixed"] * count
        if isinstance(roles, dict):
            bad = [r for r in roles if r not in REPLICA_ROLES]
            if bad:
                raise ValueError(f"unknown replica roles {bad}; expected {REPLICA_ROLES}")
            expanded: "List[str]" = []
            for role in ("prefill", "decode", "mixed"):
                expanded.extend([role] * int(roles.get(role, 0)))
        else:
            expanded = [str(r) for r in roles]
            bad = [r for r in expanded if r not in REPLICA_ROLES]
            if bad:
                raise ValueError(f"unknown replica roles {bad}; expected {REPLICA_ROLES}")
        problem = None
        if len(expanded) != count:
            problem = (
                f"replica roles {expanded} cover {len(expanded)} replicas but the fleet has {count}"
            )
        elif expanded and all(r == "prefill" for r in expanded):
            problem = (
                "an all-prefill fleet has nowhere to hand decode work off to; "
                "include at least one decode or mixed replica"
            )
        if problem:
            if strict:
                raise ValueError(problem)
            logger.warning(f"ignoring {problem}; falling back to a symmetric (all-mixed) fleet")
            return ["mixed"] * count
        return expanded

    def _new_engine(self, gen: Any, role: Optional[str]) -> Any:
        """One per-replica engine from a placed Generator — construction and
        elastic scale-up build through the same path, so a scaled-up replica
        is knob-identical to its siblings (the KV-handoff width contract)."""
        prefix_tokens = self._prefix_tokens_saved
        return ContinuousBatcher._single(
            gen,
            prefix=gen.cache_prefix(prefix_tokens) if prefix_tokens else None,
            role=role,
            **self._engine_kwargs,
        )

    # ------------------------------------------------------------------ construction

    @classmethod
    def build(
        cls,
        module: Any,
        params: Any,
        config: Any,
        *,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        quantize: Optional[str] = None,
        replicas: Optional[int] = None,
        **engine_kwargs: Any,
    ) -> "ReplicaSet":
        """Build per-replica Generators and engines from one set of weights.

        With a dp>1 ``mesh``, the replica count defaults to the mesh's
        data-parallel extent and each replica owns one TP submesh from
        :func:`slice_mesh`; a SMALLER ``replicas`` runs on the first N
        submeshes and keeps the rest as SPARES — the headroom
        :meth:`scale_to` and the autoscaler place new replicas onto at
        runtime. Without a dp mesh (``mesh`` is ``None`` or TP-only),
        ``replicas`` (default: the ``serve --dp-replicas`` export, else the
        ``--replica-roles`` total, else 1) engines are placed round-robin
        over the visible devices — each replica gets its own single-device
        mesh, so N chips serve N independent decode loops from one process.
        """
        from unionml_tpu.models.generate import Generator

        if replicas is None:
            replicas = serve_dp_replicas() or None
        if replicas is None:
            # a role spec implies its own fleet size (prefill=1,decode=3 = 4)
            roles_kw = engine_kwargs.get("roles")
            if isinstance(roles_kw, dict):
                replicas = sum(roles_kw.values()) or None
            elif isinstance(roles_kw, (list, tuple)):
                replicas = len(roles_kw) or None
            elif roles_kw is None:
                replicas = sum(serve_replica_roles().values()) or None
        spares: "List[Any]" = []
        if mesh is not None and dp_extent(mesh) > 1:
            all_submeshes = slice_mesh(mesh)
            mesh_procs = {d.process_index for d in np.asarray(mesh.devices).ravel()}
            if len(mesh_procs) > 1:
                # process-aware fleets (docs/serving.md "Multi-host fleets"): a
                # hybrid ICI/DCN mesh spans hosts, but one process can only
                # drive its OWN devices — keep the host-local submeshes and let
                # the cluster coordinator route across hosts. Replica counts
                # are then per host (the cross-host agreement in
                # serving/cluster.py hands every host the same number).
                from unionml_tpu.parallel.mesh import process_local_submeshes

                local = process_local_submeshes(all_submeshes)
                if not local:
                    raise ValueError(
                        "no replica submesh of this mesh is local to this process — "
                        "put the replica axes (dcn_data/data) on DCN "
                        "(MeshSpec.build_hybrid) so each batch slice stays host-local"
                    )
                logger.info(
                    f"multi-process mesh: this host owns replica submeshes "
                    f"{[index for index, _ in local]} of {len(all_submeshes)}"
                )
                all_submeshes = [sub for _, sub in local]
            extent = len(all_submeshes)
            if replicas is None:
                replicas = extent
            if replicas > extent:
                raise ValueError(
                    f"replicas ({replicas}) exceed the mesh's {'host-local ' if len(mesh_procs) > 1 else ''}"
                    f"data-parallel extent ({extent}); "
                    "a dp mesh cannot host more replicas than batch slices"
                )
            submeshes, spares = all_submeshes[:replicas], all_submeshes[replicas:]
        elif replicas is None or replicas == 1:
            submeshes = [mesh]
        elif mesh is not None:
            # a TP-only mesh replicated N times shares its device set — the
            # engines time-slice the same chips. Legitimate when serving is
            # host-dispatch-bound, surprising otherwise; say so once.
            logger.warning(
                f"ReplicaSet.build: {replicas} replicas over one TP-only mesh share "
                "its devices (time-sliced); add a data axis to give each replica its own chips"
            )
            submeshes = [mesh] * replicas
        else:
            submeshes = cls._single_device_meshes(replicas)
        generators = [
            Generator(module, params, config, mesh=sm, partition_rules=partition_rules, quantize=quantize)
            for sm in submeshes
        ]
        rs = cls(generators, **engine_kwargs)
        rs._replica_meshes = list(submeshes)
        rs._scale_template = {
            "module": module,
            "params": params,
            "config": config,
            "partition_rules": partition_rules,
            "quantize": quantize,
            "spares": spares,
            # a mesh-less build places replicas on per-device meshes round-
            # robin; scale-up keeps doing exactly that, so spares never run out
            "meshless": mesh is None,
        }
        return rs

    @staticmethod
    def _single_device_meshes(replicas: int) -> "List[Any]":
        """One full-axis-set 1-device mesh per replica, round-robin over the
        visible devices (the :func:`single_device_mesh` shape, one per chip)."""
        import jax
        from jax.sharding import Mesh

        from unionml_tpu.parallel.mesh import AXIS_ORDER

        devices = list(jax.devices())
        if replicas > len(devices):
            logger.warning(
                f"ReplicaSet: {replicas} replicas over {len(devices)} devices — replicas "
                "beyond the device count time-slice chips round-robin"
            )
        shape = (1,) * len(AXIS_ORDER)
        return [
            Mesh(np.asarray([devices[i % len(devices)]]).reshape(shape), AXIS_ORDER)
            for i in range(replicas)
        ]

    @classmethod
    def from_generator(
        cls, generator: Any, *, replicas: Optional[int] = None, **engine_kwargs: Any
    ) -> "ReplicaSet":
        """Re-host an existing Generator's weights as a replica set (the
        ``ContinuousBatcher`` delegation path). Params are re-placed onto each
        submesh — an fsdp-sharded tree is gathered per replica, paid once at
        construction. A pre-QUANTIZED Generator (``quantize="int8"``, by kwarg
        or the serve-wide ``UNIONML_TPU_QUANTIZE`` export) replicates too: its
        int8 tree is dequantized back to the param dtype once here and each
        replica re-quantizes its own placement — symmetric per-channel int8 is
        an exact round trip (dequantize then quantize reproduces the identical
        ``q``/``scale`` planes), so every replica serves bit-identical weights
        to the original engine."""
        params = generator.params
        quantize = getattr(generator, "quantize", None)
        if quantize is not None:
            from unionml_tpu.ops.quant import dequantize_tree

            mcfg = getattr(generator.module, "config", None)
            param_dtype = getattr(mcfg, "param_dtype", None) or getattr(mcfg, "dtype", None)
            params = dequantize_tree(params, dtype=param_dtype or "float32")
        return cls.build(
            generator.module,
            params,
            generator.config,
            mesh=generator.mesh,
            partition_rules=getattr(generator, "partition_rules", None),
            quantize=quantize,
            replicas=replicas,
            **engine_kwargs,
        )

    # ------------------------------------------------------------------ public API

    @property
    def replicas(self) -> int:
        with self._lock:
            return len(self._batchers)

    @property
    def batchers(self) -> "Tuple[Any, ...]":
        """The per-replica engines (read-only view; benchmarks introspect it)."""
        with self._lock:
            return tuple(self._batchers)

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        constraint: Optional[int] = None,
        deadline: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        export_handoff: bool = False,
        logprobs: bool = False,
    ) -> "Iterator[np.ndarray]":
        """Route a prompt to the least-loaded replica (prefix affinity
        permitting) and return its engine's token stream. Sheds with
        :class:`DeadlineExceeded` if the deadline already expired, and with
        :class:`QueueFullError` only when every replica's waiting queue is
        full — the scheduler's order is walked so one full replica never turns
        away work its siblings could take.

        With roles configured (docs/serving.md "Disaggregated and elastic
        serving"), a prompt at least ``prefill_threshold`` tokens long takes
        the DISAGGREGATED path instead: its prefill runs on a prefill-role
        replica and at admission-complete the finished KV blocks hand off to
        a decode replica — the stream's tokens (the first included) are
        bit-identical to a single mixed replica serving it, but resident
        decode streams never stall behind the prefill. A warm multi-turn
        prompt whose radix-cached run on a decode replica already covers all
        but a sub-threshold suffix skips the handoff and admits there
        directly (the cache IS the prefill)."""
        req_trace = current_trace()
        if expired(deadline):
            with self._lock:
                self.shed_deadline += 1
            if req_trace is not None:
                req_trace.event("engine.shed_deadline", phase="routing")
            raise DeadlineExceeded("deadline expired before the prompt was routed to a replica")
        with self._lock:
            batchers = list(self._batchers)
            roles = list(self._roles)
        if export_handoff:
            # the multi-host fleet's prefill leg (serving/cluster.py): run ONLY
            # the prefill on this host's best-suited replica and hand the
            # block-native payload back on the stream's ``handoff`` attribute —
            # the coordinator ships it to another HOST's import_handoff
            return self._submit_export(
                batchers, roles, prompt,
                max_new_tokens=max_new_tokens, constraint=constraint, deadline=deadline,
                tenant=tenant, priority=priority,
            )
        if any(role == "prefill" for role in roles) and not logprobs:
            # logprobs requests skip the handoff pair (the logprob column does
            # not ride the KV payload) and admit directly on a decode/mixed
            # replica through the classic walk below
            stream = self._submit_disaggregated(
                batchers, roles, prompt,
                max_new_tokens=max_new_tokens, constraint=constraint, deadline=deadline,
                req_trace=req_trace, tenant=tenant, priority=priority,
            )
            if stream is not None:
                return stream
        return self._submit_routed(
            batchers, roles, prompt,
            max_new_tokens=max_new_tokens, constraint=constraint, deadline=deadline,
            req_trace=req_trace, tenant=tenant, priority=priority, logprobs=logprobs,
        )

    def _submit_routed(
        self,
        batchers: "List[Any]",
        roles: "List[str]",
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int],
        constraint: Optional[int],
        deadline: Optional[float],
        req_trace: Any,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        logprobs: bool = False,
    ) -> "Iterator[np.ndarray]":
        """The classic least-loaded walk (PR 2), over a resize-stable snapshot.
        In a role-split fleet, prefill-role replicas are deprioritized — they
        still appear in the walk so a fleet whose decode tier is saturated
        degrades to using them rather than shedding."""
        # the routing tenant: the explicit kwarg, else the contextvar the HTTP
        # layer bound — resolved HERE (not just in the engine) because tenant
        # session affinity is a routing concern
        route_tenant = tenant if tenant is not None else current_tenant()
        loads = [batcher.load() for batcher in batchers]
        # actual per-replica cached-prefix lengths (the radix-tree probe) when
        # any engine runs a prefix cache; None keeps the LRU token-key fallback
        cached = None
        if any(getattr(b, "_radix", None) is not None for b in batchers):
            cached = [
                int(getattr(b, "cached_prefix_tokens", lambda _p: 0)(prompt))
                for b in batchers
            ]
        # per-replica SLO breach flags (cached health evaluations — cheap per
        # decision): a breaching replica is routed around, not routed to
        breaching = None
        if any(callable(getattr(b, "health", None)) for b in batchers):
            breaching = [
                callable(getattr(b, "health", None)) and b.health().get("state") == "breach"
                for b in batchers
            ]
        deprioritized = (
            [role == "prefill" for role in roles]
            if any(role == "prefill" for role in roles)
            else None
        )
        order, affinity_head = self._scheduler.order(
            loads, prompt, cached, breaching, deprioritized, tenant=route_tenant
        )
        if breaching is not None and any(breaching):
            # pure load order would have picked this replica; health demoted it
            pure_head = min(range(len(loads)), key=lambda i: (loads[i], i))
            if breaching[pure_head] and order and order[0] != pure_head:
                with self._lock:
                    self.breach_avoided += 1
        last_exc: Optional[QueueFullError] = None
        for replica in order:
            if req_trace is not None:
                # which replica, and the load it saw — recorded per ATTEMPT, so
                # a full replica's fall-through is visible on the timeline
                req_trace.event(
                    "engine.routed", replica=replica, load=round(loads[replica], 3),
                    affinity=bool(affinity_head) and replica == order[0],
                    breaching=bool(breaching[replica]) if breaching is not None else False,
                )
            try:
                stream = batchers[replica].submit(
                    prompt, max_new_tokens=max_new_tokens, constraint=constraint,
                    deadline=deadline, tenant=tenant, priority=priority,
                    logprobs=logprobs,
                )
            except TenantThrottled:
                # every replica shares the same tenant registry, so walking the
                # fleet could only re-shed — propagate the bucket's Retry-After
                # (and the tenant-limit shed reason) to the HTTP layer intact
                raise
            except QueueFullError as exc:
                last_exc = exc
                continue
            self._scheduler.note(
                replica, prompt,
                affinity=affinity_head if replica == order[0] else False,
                tenant=route_tenant,
            )
            return stream
        with self._lock:
            self.shed_queue_full += 1
        if req_trace is not None:
            req_trace.event("engine.shed_queue_full", replicas=len(batchers))
        raise QueueFullError(
            f"all {len(batchers)} replicas' waiting queues are full"
        ) from last_exc

    # ------------------------------------------------------------- disaggregation

    def _submit_export(
        self,
        batchers: "List[Any]",
        roles: "List[str]",
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int],
        constraint: Optional[int],
        deadline: Optional[float],
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> "Iterator[np.ndarray]":
        """Run an EXPORT prefill on this fleet: prefill-role replicas first,
        then least-loaded, with the usual full-queue fall-through. The
        returned stream carries the handoff payload for a DIFFERENT host's
        decode fleet — this fleet never takes the residency."""
        rank = {"prefill": 0, "mixed": 1, "decode": 2}
        loads = [batcher.load() for batcher in batchers]
        order = sorted(
            range(len(batchers)), key=lambda i: (rank.get(roles[i], 1), loads[i], i)
        )
        last_exc: Optional[QueueFullError] = None
        for replica in order:
            try:
                stream = batchers[replica].submit(
                    prompt, max_new_tokens=max_new_tokens, constraint=constraint,
                    deadline=deadline, export_handoff=True,
                    tenant=tenant, priority=priority,
                )
            except TenantThrottled:
                raise
            except QueueFullError as exc:
                last_exc = exc
                continue
            self._scheduler.note(replica, prompt)
            return stream
        with self._lock:
            self.shed_queue_full += 1
        raise QueueFullError(
            f"all {len(batchers)} replicas' waiting queues are full"
        ) from last_exc

    def import_handoff(self, payload: Dict[str, Any]) -> "Iterator[np.ndarray]":
        """Adopt another HOST's exported prefill onto this fleet's best decode
        replica (the cluster coordinator's cross-host landing path; the same
        decode → mixed → prefill fallback order as the in-fleet relay)."""
        return self._import_payload(payload, current_trace())

    def _submit_disaggregated(
        self,
        batchers: "List[Any]",
        roles: "List[str]",
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int],
        constraint: Optional[int],
        deadline: Optional[float],
        req_trace: Any,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> "Optional[Iterator[np.ndarray]]":
        """The prefill→decode handoff path; None = not applicable (short
        prompt, no viable pair, or every prefill replica's queue full — the
        caller falls back to the classic walk, so disaggregation can only
        redirect work, never shed it)."""
        prefills = [i for i, role in enumerate(roles) if role == "prefill"]
        targets = [i for i, role in enumerate(roles) if role == "decode"] or [
            i for i, role in enumerate(roles) if role == "mixed"
        ]
        if not prefills or not targets or len(prompt) < self._prefill_threshold:
            return None
        loads = [batcher.load() for batcher in batchers]
        # warm multi-turn shortcut: a decode replica that already caches all
        # but a sub-threshold suffix of this prompt admits it directly — its
        # radix gather replaces the prefill a prefill replica would re-run
        warm = [
            (int(getattr(batchers[t], "cached_prefix_tokens", lambda _p: 0)(prompt)), -loads[t], t)
            for t in targets
            if getattr(batchers[t], "_radix", None) is not None
        ]
        if warm:
            cached_len, _, warm_t = max(warm)
            # direct-admit when the cache already covers MORE than half the
            # prompt (or the uncached suffix is sub-threshold): the residual
            # prefill there is cheaper than re-running the whole prompt on
            # the prefill tier plus a cross-replica transfer
            suffix = len(prompt) - cached_len
            if cached_len > 0 and suffix < max(self._prefill_threshold, (len(prompt) + 1) // 2):
                try:
                    stream = batchers[warm_t].submit(
                        prompt, max_new_tokens=max_new_tokens,
                        constraint=constraint, deadline=deadline,
                        tenant=tenant, priority=priority,
                    )
                except TenantThrottled:
                    raise  # the bucket sheds fleet-wide; see _submit_routed
                except QueueFullError:
                    pass
                else:
                    if req_trace is not None:
                        req_trace.event(
                            "engine.routed", replica=warm_t, load=round(loads[warm_t], 3),
                            role=roles[warm_t], cached=cached_len,
                        )
                    self._scheduler.note(
                        warm_t, prompt,
                        tenant=tenant if tenant is not None else current_tenant(),
                    )
                    with self._lock:
                        self.handoff_shortcuts += 1
                    return stream
        for p in sorted(prefills, key=lambda i: (loads[i], i)):
            if req_trace is not None:
                req_trace.event(
                    "engine.routed", replica=p, load=round(loads[p], 3), role="prefill",
                )
            try:
                pstream = batchers[p].submit(
                    prompt, max_new_tokens=max_new_tokens, constraint=constraint,
                    deadline=deadline, export_handoff=True,
                    tenant=tenant, priority=priority,
                )
            except TenantThrottled:
                raise
            except QueueFullError:
                continue
            self._scheduler.note(p, prompt)
            with self._lock:
                self.handoff_routes += 1
            return self._relay(pstream, req_trace)
        return None  # every prefill replica full: degrade to the classic walk

    def _relay(self, pstream: Any, req_trace: Any) -> "Iterator[np.ndarray]":
        """Stitch the prefill replica's one-token export stream and the decode
        replica's resident stream into one consumer-facing iterator. Closing
        the relay (client disconnect) closes whichever leg is active, so the
        producer never decodes to a dead connection."""
        active = pstream
        try:
            for item in pstream:
                yield item
            payload = pstream.handoff
            if payload is None:
                return  # finished outright at the prompt-sampled token
            dstream = self._import_payload(payload, req_trace)
            active = dstream
            for item in dstream:
                yield item
        finally:
            try:
                active.close()
            except Exception:  # pragma: no cover - defensive
                pass

    def _import_payload(self, payload: Dict[str, Any], req_trace: Any) -> Any:
        """Land an exported prefill on the best live decode replica (decode →
        mixed → prefill fallback order; quiescing/closed replicas are walked
        past, so a mid-relay resize re-targets instead of failing)."""
        with self._lock:
            batchers = list(self._batchers)
            roles = list(self._roles)
        rank = {"decode": 0, "mixed": 1, "prefill": 2}
        loads = [batcher.load() for batcher in batchers]
        order = sorted(
            range(len(batchers)), key=lambda i: (rank.get(roles[i], 1), loads[i], i)
        )
        last_exc: Optional[BaseException] = None
        for t in order:
            try:
                stream = batchers[t].import_handoff(payload)
            except (QueueFullError, RuntimeError) as exc:
                last_exc = exc
                continue
            if req_trace is not None:
                req_trace.event(
                    "engine.routed", replica=t, load=round(loads[t], 3), role=roles[t],
                    handoff=True,
                )
            # the DECODE replica is where the tenant's session KV ends up: the
            # tenant-affinity map records it, not the prefill leg
            self._scheduler.note(t, payload.get("prompt"), tenant=payload.get("tenant"))
            return stream
        raise RuntimeError(
            f"no replica of {len(batchers)} could adopt the handed-off prefill"
        ) from last_exc

    def warmup(self) -> None:
        """Resolve every replica's admission/prefill/decode programs,
        concurrently — replicas own disjoint engines (and usually disjoint
        devices), so their compile walls overlap instead of stacking. With
        the AOT store armed (``aot=`` / ``UNIONML_TPU_AOT_PRELOAD``) each
        replica preloads serialized executables keyed to its own submesh —
        a restarted server with the same fleet layout warms load-bound."""
        from concurrent.futures import ThreadPoolExecutor

        batchers = self.batchers
        with ThreadPoolExecutor(max_workers=len(batchers)) as pool:
            # list() propagates the first failure instead of dropping it
            list(pool.map(lambda batcher: batcher.warmup(), batchers))

    def load(self) -> float:
        """Aggregate token-weighted load (the signal a layer above a fleet of
        ReplicaSets would schedule on, mirroring the engine's own)."""
        return sum(batcher.load() for batcher in self.batchers)

    def cached_prefix_tokens(self, prompt: Sequence[int]) -> int:
        """Longest radix-cached run of ``prompt`` across this fleet's replicas
        — the per-HOST quantity the cluster coordinator's fleet-global prefix
        routing compares (serving/cluster.py). 0 with no prefix caches."""
        best = 0
        for batcher in self.batchers:
            probe = getattr(batcher, "cached_prefix_tokens", None)
            if callable(probe):
                best = max(best, int(probe(prompt)))
        return best

    def health(self) -> Dict[str, Any]:
        """Fleet health (observability/health.py): mean + worst per-replica
        scores and the worst SLO state — the ``GET /healthz`` body."""
        from unionml_tpu.observability.health import fleet_health

        return fleet_health(self)

    def configure_slo(self, config: Any, replica: Optional[int] = None) -> None:
        """Swap SLO targets on every replica (or just ``replica`` — per-role
        targets for heterogeneous fleets) at runtime."""
        batchers = self.batchers
        targets = batchers if replica is None else [batchers[replica]]
        for batcher in targets:
            batcher.configure_slo(config)

    # ------------------------------------------------------------------ elasticity

    @property
    def roles(self) -> "List[str]":
        """Per-replica roles (``prefill``/``decode``/``mixed``), index-aligned
        with :attr:`batchers`."""
        with self._lock:
            return list(self._roles)

    def scale_to(self, n: int, *, role: Optional[str] = None, timeout: float = 120.0) -> int:
        """Resize the fleet to ``n`` replicas at runtime, returning the new
        count. Scale-UP places the construction template's params onto a
        spare submesh (or, mesh-less, the next device round-robin), warms the
        new engine up, and only then joins it to the scheduler — the first
        routed request never pays a cold compile. With the AOT store armed
        the warmup itself preloads serialized executables keyed to the new
        replica's submesh: a submesh the store has seen (an earlier scale-up,
        a previous process with the same fleet layout) joins without a single
        fresh XLA trace, so autoscaler oscillation costs milliseconds, not
        compile walls. ``role`` tags the added
        replicas (default: ``decode`` in a role-split fleet, ``mixed``
        otherwise). Scale-DOWN drains the TAIL replica with PR 1's machinery:
        it is unrouted and quiesced first (new submits bounce to siblings),
        residents and already-queued work finish within ``timeout``, then the
        engine closes and its submesh returns to the spare pool — zero
        in-flight streams lost. Serialized against the autoscaler; safe from
        any thread."""
        if n < 1:
            raise ValueError("a fleet cannot scale below 1 replica")
        if role is not None and role not in REPLICA_ROLES:
            raise ValueError(f"role must be one of {REPLICA_ROLES}, got {role!r}")
        with self._scale_lock:
            while True:
                with self._lock:
                    cur = len(self._batchers)
                if cur == n:
                    return n
                if n > cur:
                    self._add_replica(role)
                    with self._lock:
                        self.scaled_up += 1
                else:
                    self._remove_replica(timeout)
                    with self._lock:
                        self.scaled_down += 1

    def spare_capacity(self) -> int:
        """Replicas :meth:`scale_to` could still add: spare submeshes for a
        dp-mesh fleet, unbounded (-1 reported as a large sentinel is avoided —
        the visible device count) for a mesh-less one, 0 when no construction
        template was retained."""
        with self._lock:
            template = self._scale_template
            if template is None:
                return 0
            if template["meshless"]:
                import jax

                return len(jax.devices())  # round-robin: always placeable
            return len(template["spares"])

    def _add_replica(self, role: Optional[str]) -> None:
        """Build, warm, and join one replica (the _scale_lock holder)."""
        from unionml_tpu.models.generate import Generator

        with self._lock:
            template = self._scale_template
            if template is None:
                raise RuntimeError(
                    "scale-up needs the construction template a ReplicaSet.build()/"
                    "from_generator() fleet retains; this set was built from "
                    "pre-made generators/engines"
                )
            index = len(self._batchers)
            has_roles = any(r != "mixed" for r in self._roles)
            if template["spares"]:
                mesh = template["spares"].pop(0)
            elif template["meshless"]:
                mesh = self._single_device_meshes(index + 1)[index]
            else:
                raise RuntimeError(
                    "no spare submesh to place a new replica on (the dp mesh is fully "
                    "occupied); build with fewer initial replicas to keep headroom"
                )
        resolved = role or ("decode" if has_roles else "mixed")
        try:
            gen = Generator(
                template["module"], template["params"], template["config"],
                mesh=mesh, partition_rules=template["partition_rules"],
                quantize=template["quantize"],
            )
            engine = self._new_engine(gen, resolved if (has_roles or role) else None)
            # warm BEFORE joining the scheduler: the replica's first routed
            # request must never pay the cold XLA compile (ROADMAP item 5's
            # concern, held to at resize time)
            engine.warmup()
        except BaseException:
            with self._lock:
                if self._scale_template is template and mesh is not None and not template["meshless"]:
                    template["spares"].insert(0, mesh)
            raise
        with self._lock:
            self._batchers.append(engine)
            self._roles.append(resolved)
            self._replica_meshes.append(mesh)
            self._scheduler.resize(len(self._batchers))
        logger.info(f"replica {index} joined the fleet (role={resolved})")

    def _remove_replica(self, timeout: float) -> None:
        """Unroute, drain, and close the tail replica (the _scale_lock
        holder). The tail is the removal point so surviving replicas keep
        their scheduler indexes (and telemetry) stable — and because role
        expansion orders prefill first, the capacity tier drains before the
        prefill tier."""
        with self._lock:
            if len(self._batchers) <= 1:
                raise ValueError("a fleet cannot scale below 1 replica")
            engine = self._batchers.pop()
            role = self._roles.pop()
            mesh = self._replica_meshes.pop()
            self._scheduler.resize(len(self._batchers))
            template = self._scale_template
        # quiesce BEFORE draining: a routing snapshot taken just before the
        # pop may still hold this engine — its submit now sheds QueueFullError
        # and the scheduler walk lands the request on a surviving sibling
        engine.quiesce()
        deadline = time.monotonic() + max(timeout, 0.0)
        while time.monotonic() < deadline:
            resident, waiting = engine.occupancy()
            if resident == 0 and waiting == 0:
                break
            time.sleep(0.01)
        else:
            resident, waiting = engine.occupancy()
            logger.warning(
                f"scale-down drain timed out with {resident} resident / {waiting} waiting "
                "streams; closing anyway (stragglers finish on the engine thread)"
            )
        engine.close(wait=True, timeout=max(deadline - time.monotonic(), 1.0))
        if template is not None and mesh is not None and not template["meshless"]:
            with self._lock:
                template["spares"].insert(0, mesh)
        logger.info(f"replica drained and left the fleet (role={role})")

    # ------------------------------------------------------------------ autoscaler

    def configure_autoscaler(
        self,
        *,
        high: float,
        low: float = 0.0,
        interval_s: float = 10.0,
        min_replicas: int = 1,
        max_replicas: int = 0,
        role: str = "decode",
    ) -> "ReplicaSet":
        """Arm (or retune) the autoscaler: every ``interval_s`` the loop reads
        the fleet's windowed pressure — per-replica token-weighted ``load()``,
        forced over the high watermark while any replica's SLO state is
        *breach* (PR 8's ``health()`` as the scale-up trigger) — and resizes
        one replica at a time: above ``high`` it adds a ``role`` replica (if
        spare capacity remains and ``max_replicas`` allows; 0 = capacity-
        bound), below ``low`` it drains one (never under ``min_replicas``;
        ``low=0`` disables scale-down). The loop thread is owned and joined
        by :meth:`close`."""
        if high <= 0:
            raise ValueError("high watermark must be > 0 (use close/False to disable)")
        if low < 0 or low >= high:
            raise ValueError("low watermark must be in [0, high)")
        if interval_s <= 0 or min_replicas < 1 or max_replicas < 0:
            raise ValueError("interval_s > 0, min_replicas >= 1, max_replicas >= 0 required")
        if role not in REPLICA_ROLES:
            raise ValueError(f"role must be one of {REPLICA_ROLES}, got {role!r}")
        with self._lock:
            self._autoscale = {
                "high": float(high), "low": float(low), "interval_s": float(interval_s),
                "min_replicas": int(min_replicas), "max_replicas": int(max_replicas),
                "role": role,
            }
            if self._autoscale_thread is None:
                self._autoscale_thread = threading.Thread(
                    target=self._autoscale_loop, daemon=True
                )
                self._autoscale_thread.start()
        return self

    def _autoscale_pressure(self) -> float:
        """The watermark quantity: mean per-replica token-weighted load,
        saturated past the high watermark while any replica breaches its SLO
        (latency burn means the fleet is undersized even if raw occupancy
        looks moderate). Overridable by tests and bespoke policies."""
        with self._lock:
            batchers = list(self._batchers)
            config = self._autoscale
        load = sum(batcher.load() for batcher in batchers) / max(len(batchers), 1)
        breaching = any(
            callable(getattr(b, "health", None)) and b.health().get("state") == "breach"
            for b in batchers
        )
        if breaching and config is not None:
            load = max(load, config["high"] + 1.0)
        return load

    def _autoscale_loop(self) -> None:
        while True:
            with self._lock:
                config = self._autoscale
            interval = config["interval_s"] if config is not None else 1.0
            if self._autoscale_stop.wait(interval):
                return
            try:
                self._autoscale_step()
            except Exception:  # pragma: no cover - the loop must survive
                logger.exception("autoscaler step failed")

    def _autoscale_step(self) -> None:
        with self._lock:
            config = self._autoscale
            n = len(self._batchers)
        if config is None:
            return
        pressure = self._autoscale_pressure()
        ceiling = config["max_replicas"] or (n + self.spare_capacity())
        if pressure > config["high"] and n < ceiling and self.spare_capacity() > 0:
            logger.info(
                f"autoscaler: pressure {pressure:.2f} > high {config['high']:.2f}; "
                f"scaling {n} -> {n + 1}"
            )
            self.scale_to(n + 1, role=config["role"])
        elif config["low"] > 0 and pressure < config["low"] and n > config["min_replicas"]:
            logger.info(
                f"autoscaler: pressure {pressure:.2f} < low {config['low']:.2f}; "
                f"scaling {n} -> {n - 1}"
            )
            self.scale_to(n - 1)

    def tenant_slo(self) -> "Dict[str, Any]":
        """Fleet-wide per-tenant SLO verdicts: the worst replica's entry per
        tenant (observability/health.merge_tenant_slo) — ``{}`` when no
        tenant carries per-tenant targets, so the section stays absent on
        target-less fleets."""
        from unionml_tpu.observability.health import merge_tenant_slo

        return merge_tenant_slo(list(self.batchers))

    def tenant_census(self) -> "Dict[str, Dict[str, int]]":
        """Fleet-wide live per-tenant stream counts (multi-tenant QoS,
        ``/debug/fleet``): each replica's bounded census summed — empty when
        no identified-tenant traffic is in flight."""
        census: "Dict[str, Dict[str, int]]" = {}
        for batcher in self.batchers:
            fn = getattr(batcher, "tenant_census", None)
            if not callable(fn):
                continue
            for tenant, counts in fn().items():
                entry = census.setdefault(tenant, {"resident": 0, "waiting": 0})
                for key, value in counts.items():
                    entry[key] = entry.get(key, 0) + int(value)
        return census

    def queued_prefill_tokens(self) -> int:
        """Fleet-wide prefill backlog in tokens (engines that predate the
        token accounting report 0)."""
        return sum(
            int(getattr(batcher, "queued_prefill_tokens", lambda: 0)())
            for batcher in self.batchers
        )

    def replica_loads(self) -> "List[Dict[str, Any]]":
        """Per-replica occupancy for live gauges: cheap (no full stats dict),
        evaluated at ``/metrics`` snapshot time."""
        with self._lock:
            snapshot = list(zip(self._batchers, self._roles))
        out = []
        for i, (batcher, role) in enumerate(snapshot):
            resident, waiting = batcher.occupancy()
            out.append(
                {
                    "replica": i,
                    "role": role,
                    "resident": resident,
                    "waiting": waiting,
                    "free_slots": max(int(getattr(batcher, "slots", 0)) - resident, 0),
                    "prefill_backlog_tokens": int(
                        getattr(batcher, "queued_prefill_tokens", lambda: 0)()
                    ),
                    "shed_queue_full": getattr(batcher, "shed_queue_full", 0),
                    "shed_deadline": getattr(batcher, "shed_deadline", 0),
                }
            )
        return out

    def stats(self) -> Dict[str, Any]:
        """Fleet snapshot for ``/metrics``: aggregates plus per-replica engine
        stats and the scheduler's routing telemetry."""
        with self._lock:
            batchers = list(self._batchers)
            roles = list(self._roles)
        per_replica = [batcher.stats() for batcher in batchers]

        def total(key: str) -> int:
            return sum(int(entry.get(key) or 0) for entry in per_replica)

        with self._lock:
            shed_deadline, shed_queue_full = self.shed_deadline, self.shed_queue_full
            breach_avoided = self.breach_avoided
            handoff_routes, handoff_shortcuts = self.handoff_routes, self.handoff_shortcuts
            scaled_up, scaled_down = self.scaled_up, self.scaled_down
            autoscale = dict(self._autoscale) if self._autoscale is not None else None
        # fleet health headline (per-replica detail rides per_replica's own
        # rates/slo sections): strip the replicas list — stats() must not
        # duplicate every engine's health payload
        fleet = {
            key: value
            for key, value in self.health().items()
            if key != "replicas"
        }
        def total_prefill(key: str) -> int:
            return sum(
                int((entry.get("prefill") or {}).get(key) or 0) for entry in per_replica
            )

        has_roles = any(role != "mixed" for role in roles)
        return {
            "replicas": len(batchers),
            "scheduler": self._scheduler.stats(),
            # disaggregated serving: role census, routing counters, and the
            # fleet-wide handoff totals (per-replica transfer latency rides
            # per_replica's own handoff sections) — present only in role-split
            # fleets, so symmetric fleets keep today's stats byte-for-byte
            **(
                {
                    "roles": {
                        role: sum(1 for r in roles if r == role)
                        for role in ("prefill", "decode", "mixed")
                    },
                    "handoffs": {
                        "routed": handoff_routes,
                        "shortcuts": handoff_shortcuts,
                        "exported": sum(
                            int((entry.get("handoff") or {}).get("exported") or 0)
                            for entry in per_replica
                        ),
                        "imported": sum(
                            int((entry.get("handoff") or {}).get("imported") or 0)
                            for entry in per_replica
                        ),
                    },
                }
                if has_roles
                else {}
            ),
            # elastic resize: lifetime scale events + remaining headroom, and
            # the armed watermarks (absent while the autoscaler is off)
            **(
                {
                    "resize": {
                        "scaled_up": scaled_up,
                        "scaled_down": scaled_down,
                        "spare_capacity": self.spare_capacity(),
                        **({"autoscaler": autoscale} if autoscale is not None else {}),
                    }
                }
                if (scaled_up or scaled_down or autoscale is not None)
                else {}
            ),
            "slots": total("slots"),
            "resident": total("resident"),
            "waiting": total("waiting"),
            "admitting": total("admitting"),
            "decode_dispatches": total("decode_dispatches"),
            "decoded_rows": total("decoded_rows"),
            # stall-free admission, fleet-wide: chunk counters + the token
            # backlog the token-weighted routing acts on (per-replica TTFT/TBT
            # percentiles stay under per_replica — percentiles don't sum)
            "prefill_chunks": total_prefill("chunks"),
            "prefill_backlog_tokens": total_prefill("backlog_tokens"),
            # fleet-wide radix prefix-cache totals (present only when at least
            # one replica runs the cache, so cache-off fleets keep today's
            # stats byte-for-byte; per-replica detail stays under per_replica)
            **(
                {
                    "prefix_cache": {
                        key: sum(
                            int((entry.get("prefix_cache") or {}).get(key) or 0)
                            for entry in per_replica
                        )
                        for key in ("hits", "misses", "tokens_avoided", "evictions",
                                    "cow_copies", "cached_blocks", "cached_bytes",
                                    "pinned_blocks")
                    }
                }
                if any("prefix_cache" in entry for entry in per_replica)
                else {}
            ),
            # fleet-wide AOT preload totals (present only when some replica
            # runs a program store — store-off fleets keep today's stats
            # byte-for-byte; per-replica load/compile latency windows stay
            # under per_replica, since percentiles don't sum)
            **(
                {
                    "aot": {
                        key: sum(
                            int((entry.get("aot") or {}).get(key) or 0)
                            for entry in per_replica
                        )
                        for key in ("programs_loaded", "programs_compiled",
                                    "programs_serialized", "load_failures",
                                    "serialize_failures")
                    }
                }
                if any("aot" in entry for entry in per_replica)
                else {}
            ),
            # fleet-wide multi-tenant QoS totals (present only when some
            # replica reports a tenancy section — QoS-off fleets keep today's
            # stats byte-for-byte; per-tenant buckets ride the app's registry)
            **(
                {
                    "tenancy": {
                        key: sum(
                            int((entry.get("tenancy") or {}).get(key) or 0)
                            for entry in per_replica
                        )
                        for key in ("shed_tenant_limit", "priority_preemptions")
                    }
                }
                if any("tenancy" in entry for entry in per_replica)
                else {}
            ),
            # fleet-wide per-tenant SLO verdicts (worst replica wins per
            # tenant); absent unless some replica tracks tenant targets —
            # per-replica detail stays under per_replica
            **(
                {"tenant_slo": self.tenant_slo()}
                if any("tenant_slo" in entry for entry in per_replica)
                else {}
            ),
            # fleet-level sheds (all replicas full / expired before routing) on
            # top of each engine's own counters
            "shed_queue_full": shed_queue_full + total("shed_queue_full"),
            "shed_deadline": shed_deadline + total("shed_deadline"),
            # fleet health score/state + how often routing walked around a
            # breaching replica (the observability→routing feedback, observable)
            "health": fleet,
            "breach_avoided": breach_avoided,
            "per_replica": per_replica,
        }

    def close(self, wait: bool = True, timeout: float = 120.0) -> None:
        """Drain every replica: stop the autoscaler loop (a resize must not
        race the shutdown), stop admissions fleet-wide (no stragglers
        re-routed into a replica that is about to close), then wait out the
        drains under one shared timeout."""
        self._autoscale_stop.set()
        thread = self._autoscale_thread
        if thread is not None:
            thread.join(timeout=10.0)
        with self._lock:
            batchers = list(self._batchers)
        for batcher in batchers:
            batcher.close(wait=False)
        if wait:
            deadline = time.monotonic() + timeout
            for batcher in batchers:
                batcher.close(wait=True, timeout=max(deadline - time.monotonic(), 0.0))
