"""Data-parallel replica serving: N continuous engines behind one scheduler.

The continuous engine (:mod:`unionml_tpu.serving.continuous`) shards over
model/TP axes only — a ``[1, ...]`` admission row cannot split a batch axis, so
a mesh with ``data``/``fsdp`` > 1 used to be rejected outright and multi-chip
serving was TP-only. At fleet scale the first knob an operator reaches for is
the other one: *replicas*. Orca (OSDI '22) and vLLM (SOSP '23) both assume the
iteration-level scheduler sits above a pool of replicated engines; this module
is that layer.

Design:

- :func:`slice_mesh` cuts the device mesh along its batch axes (``dcn_data``,
  ``data``, ``fsdp``) into per-replica TP submeshes — each keeps the full axis
  set with batch axes at 1, so every Generator code path (TP collectives,
  sequence-parallel prefill, paged pools) runs unchanged inside a replica;
- :class:`ReplicaSet` builds one Generator + :class:`ContinuousBatcher` per
  submesh (params re-placed per slice; within a replica the batch axes are 1,
  so placement replicates) and owns their shared lifecycle (warmup in
  parallel, drain on close);
- :class:`ReplicaScheduler` admits requests least-loaded-first — load is a
  replica's live residents plus live waiters PLUS its pending prefill
  backlog in tokens (``ContinuousBatcher.load()``'s token weighting), so two
  replicas with equal waiter counts but a 10k-token vs a 10-token queued
  prompt do not tie — with prefix-affinity routing so shared-prefix
  requests land on the replica whose KV pool already holds that prefix. With
  per-engine radix prefix caches on (``prefix_cache=True``), affinity routes
  on each replica's ACTUAL cached-prefix length for the prompt (the radix
  probe ``cached_prefix_tokens``) — the scheduler is the cross-replica tier
  of the same cache; without them the bounded-LRU token-key heuristic
  (``affinity_tokens``) remains the fallback. The affinity margin check and
  the hotspot fallback rank on the SAME token-weighted loads, so a fallback
  never lands on a replica with a deep prefill backlog that mere waiter
  counts would hide.

Overload posture composes with PR 1's machinery: an expired deadline sheds
before routing (:class:`DeadlineExceeded`, HTTP 503), and a prompt is shed
with :class:`QueueFullError` (HTTP 429) only when EVERY replica's bounded
waiting queue is full — the scheduler walks replicas in load order, so a
single hot replica never turns away work the rest of the fleet could take.

``ContinuousBatcher(generator, ...)`` with a dp>1 mesh (or with the serve
CLI's ``--dp-replicas`` exported) transparently constructs a ReplicaSet —
existing apps opt into replica serving by mesh shape or CLI flag, with no code
changes; the set mirrors the engine's public surface (``submit`` / ``warmup``
/ ``stats`` / ``close``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.defaults import serve_dp_replicas
from unionml_tpu.observability.trace import current_trace
from unionml_tpu.parallel.mesh import BATCH_AXES
from unionml_tpu.serving.continuous import ContinuousBatcher
from unionml_tpu.serving.overload import DeadlineExceeded, QueueFullError, expired

__all__ = ["ReplicaScheduler", "ReplicaSet", "dp_extent", "slice_mesh"]


def dp_extent(mesh: Any) -> int:
    """Product of a mesh's batch (data-parallel) axis sizes — the natural
    replica count of :func:`slice_mesh`. 1 for ``None`` or a TP-only mesh."""
    if mesh is None:
        return 1
    extent = 1
    for axis in BATCH_AXES:
        extent *= int(mesh.shape.get(axis, 1))
    return extent


def slice_mesh(mesh: Any, replicas: Optional[int] = None) -> "List[Any]":
    """Slice a device mesh along its batch axes into per-replica TP submeshes.

    Each submesh keeps the mesh's full axis-name set with every batch axis at
    size 1 (``model``/``sequence``/``expert``/``pipe`` extents unchanged), so a
    Generator built over it behaves exactly like a TP-only engine. ``replicas``
    must equal the batch-axis product when given — a partial slice would leave
    a >1 batch axis inside a replica, which the engine cannot serve.
    """
    from jax.sharding import Mesh

    names = tuple(mesh.axis_names)
    devices = np.asarray(mesh.devices)
    batch_dims = [i for i, n in enumerate(names) if n in BATCH_AXES and devices.shape[i] > 1]
    total = int(np.prod([devices.shape[i] for i in batch_dims])) if batch_dims else 1
    if replicas is None:
        replicas = total
    if replicas != total:
        raise ValueError(
            f"replicas ({replicas}) must equal the mesh's data-parallel extent ({total}: "
            f"the product of its {'/'.join(BATCH_AXES)} axes) — a partial slice would leave "
            "a >1 batch axis inside a replica"
        )
    if total == 1:
        return [mesh]
    out = []
    batch_shape = tuple(devices.shape[i] for i in batch_dims)
    for flat in range(total):
        index = np.unravel_index(flat, batch_shape)
        slicer: "List[Any]" = [slice(None)] * devices.ndim
        for dim, j in zip(batch_dims, index):
            slicer[dim] = slice(int(j), int(j) + 1)
        out.append(Mesh(devices[tuple(slicer)], names))
    return out


class ReplicaScheduler:
    """Least-loaded-first routing over N replicas, with optional prefix affinity.

    Load is supplied by the caller per decision (the engine's token-weighted
    ``load()``: live residents + live waiters + prefill backlog tokens
    normalized by the admission chunk — ints or floats both rank); ties break
    toward the lowest index, so an idle fleet fills in order and drains
    evenly. Both the affinity-margin comparison and the hotspot-fallback
    ranking use these same loads, so mixed prompt lengths route sensibly on
    every path. ``affinity_tokens > 0`` enables prefix-affinity
    routing: requests sharing their first ``affinity_tokens`` prompt tokens are
    steered to the replica that last served that prefix — its KV pool already
    holds those rows/pages (shared-prefix pages in paged mode), so the prefill
    is warm — unless that replica is more than ``affinity_margin`` requests
    busier than the least-loaded one. The margin keeps a popular prefix from
    turning one replica into a hotspot while the rest idle; the affinity map is
    a bounded LRU, so unbounded prefix cardinality cannot grow host memory.
    """

    def __init__(
        self,
        replicas: int,
        *,
        affinity_tokens: int = 0,
        affinity_margin: int = 2,
        affinity_capacity: int = 4096,
    ):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if affinity_tokens < 0 or affinity_margin < 0 or affinity_capacity < 1:
            raise ValueError("affinity knobs must be non-negative (capacity >= 1)")
        self.replicas = replicas
        self.affinity_tokens = affinity_tokens
        self.affinity_margin = affinity_margin
        self._affinity_capacity = affinity_capacity
        self._affinity: "OrderedDict[Tuple[int, ...], int]" = OrderedDict()
        self._lock = threading.Lock()
        #: routing telemetry: successful submissions per replica, and how many
        #: rode the affinity map vs plain least-loaded
        self.submitted = [0] * replicas
        self.affinity_hits = 0

    def _key(self, prompt: Optional[Sequence[int]]) -> Optional[Tuple[int, ...]]:
        if not self.affinity_tokens or prompt is None:
            return None
        if len(prompt) < self.affinity_tokens:
            return None  # shorter than the affinity window: nothing shared to exploit
        return tuple(int(t) for t in prompt[: self.affinity_tokens])

    def order(
        self,
        loads: Sequence[int],
        prompt: Optional[Sequence[int]] = None,
        cached: Optional[Sequence[int]] = None,
        breaching: Optional[Sequence[bool]] = None,
    ) -> "Tuple[List[int], bool]":
        """``(indices to try best-first, head_is_affinity)``. The caller walks
        the list so a full (QueueFullError) replica falls through to the
        next-least-loaded instead of shedding work the rest of the fleet could
        take; the flag marks whether the head came from affinity routing (for
        hit accounting) rather than pure load order.

        ``cached`` — per-replica ACTUAL cached-prefix token counts (each
        engine's ``cached_prefix_tokens(prompt)`` radix probe) — takes
        precedence over the token-key LRU heuristic: the replica whose KV pool
        already holds the longest run of this prompt is preferred, unless it
        is more than ``affinity_margin`` load units busier than the least
        loaded (the same hotspot guard). The LRU map remains the fallback for
        engines without a prefix cache.

        ``breaching`` — per-replica SLO-breach flags (each engine's
        ``health()["state"] == "breach"``, the observability→routing feedback)
        — deprioritizes a breaching replica below EVERY non-breaching one
        regardless of load, and disqualifies it from heading the order via
        affinity: sending a warm-prefix request to a replica that is already
        missing its latency targets would trade a prefill for a breach. A
        breaching replica still appears in the walk order, so a fleet that is
        breaching everywhere degrades to plain least-loaded rather than
        shedding."""
        avoid = (
            [bool(flag) for flag in breaching]
            if breaching is not None and len(breaching) == len(loads)
            else [False] * len(loads)
        )
        ranked = sorted(range(len(loads)), key=lambda i: (avoid[i], loads[i], i))
        if cached is not None and len(cached) == len(loads) and max(cached, default=0) > 0:
            # warm replicas that are NOT breaching compete on cached length; a
            # breaching replica's warm cache never heads the order
            candidates = [i for i in range(len(loads)) if cached[i] > 0 and not avoid[i]]
            if candidates:
                preferred = min(candidates, key=lambda i: (-cached[i], loads[i], i))
                if loads[preferred] <= loads[ranked[0]] + self.affinity_margin:
                    return [preferred] + [i for i in ranked if i != preferred], True
            return ranked, False
        key = self._key(prompt)
        if key is not None:
            with self._lock:
                preferred = self._affinity.get(key)
            if (
                preferred is not None
                and not avoid[preferred]
                and loads[preferred] <= loads[ranked[0]] + self.affinity_margin
            ):
                return [preferred] + [i for i in ranked if i != preferred], True
        return ranked, False

    def note(self, replica: int, prompt: Optional[Sequence[int]] = None, *, affinity: bool = False) -> None:
        """Record a successful routing decision (updates the affinity map)."""
        key = self._key(prompt)
        with self._lock:
            self.submitted[replica] += 1
            if affinity:
                self.affinity_hits += 1
            if key is not None:
                self._affinity[key] = replica
                self._affinity.move_to_end(key)
                while len(self._affinity) > self._affinity_capacity:
                    self._affinity.popitem(last=False)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "policy": "least-loaded",
                "submitted": list(self.submitted),
                "affinity_tokens": self.affinity_tokens,
                "affinity_hits": self.affinity_hits,
                "affinity_entries": len(self._affinity),
            }


class ReplicaSet:
    """N data-parallel :class:`ContinuousBatcher` replicas behind one scheduler.

    >>> rs = ReplicaSet.build(module, params, gen_config,
    ...                       mesh=MeshSpec(data=2, model=2).build(),
    ...                       partition_rules=llama_partition_rules(),
    ...                       slots=4, decode_chunk=8)
    >>> for chunk in rs.submit([1, 5, 9]):
    ...     ...
    >>> rs.close()

    The public surface mirrors the single engine (``submit`` / ``warmup`` /
    ``stats`` / ``close``), so everything that composes with a
    ``ContinuousBatcher`` — the stream-predictor route, ``/metrics``, graceful
    drain — composes with a replica set unchanged. Engine knobs (``slots``,
    ``decode_chunk``, ``block_size``, ``pool_blocks``, ``max_waiting``,
    ``admit_chunk``/``prefill_budget``/``max_admissions`` — stall-free
    admission — ``prefix_cache`` — the radix prefix cache, see
    serving/continuous.py — ``slo`` — the fleet health & SLO engine —
    and ``prefix``) apply PER REPLICA; a shared ``prefix`` (token ids or a
    ``PrefixCache`` built with ``cache_prefix``) is prefilled once per replica
    at construction, since cache rows cannot cross submeshes.
    """

    def __init__(
        self,
        generators: Optional[Sequence[Any]] = None,
        *,
        engines: Optional[Sequence[Any]] = None,
        slots: int = 4,
        decode_chunk: int = 8,
        prefix: Optional[Any] = None,
        block_size: Optional[int] = None,
        pool_blocks: Optional[int] = None,
        max_waiting: Optional[int] = None,
        admit_chunk: Optional[int] = None,
        prefill_budget: Optional[int] = None,
        max_admissions: Optional[int] = None,
        affinity_tokens: int = 0,
        affinity_margin: int = 2,
        trace: Optional[bool] = None,
        prefix_cache: Optional[bool] = None,
        slo: Optional[Any] = None,
    ):
        if (generators is None) == (engines is None):
            raise ValueError("pass exactly one of generators= or engines=")
        if engines is not None:
            self._batchers: "List[Any]" = list(engines)
        else:
            prefix_tokens = self._prefix_tokens(prefix)
            self._batchers = []
            try:
                for gen in generators:
                    self._batchers.append(
                        ContinuousBatcher._single(
                            gen,
                            slots=slots,
                            decode_chunk=decode_chunk,
                            prefix=gen.cache_prefix(prefix_tokens) if prefix_tokens else None,
                            block_size=block_size,
                            pool_blocks=pool_blocks,
                            max_waiting=max_waiting,
                            admit_chunk=admit_chunk,
                            prefill_budget=prefill_budget,
                            max_admissions=max_admissions,
                            trace=trace,
                            prefix_cache=prefix_cache,
                            slo=slo,
                        )
                    )
            except BaseException:
                for batcher in self._batchers:
                    batcher.close(wait=False)
                raise
        if not self._batchers:
            raise ValueError("a ReplicaSet needs at least one replica")
        self._scheduler = ReplicaScheduler(
            len(self._batchers), affinity_tokens=affinity_tokens, affinity_margin=affinity_margin
        )
        self._lock = threading.Lock()
        #: fleet-level sheds: a deadline that expired before routing, and
        #: prompts turned away because EVERY replica's waiting queue was full
        #: (per-replica counters additionally record each engine's own sheds)
        self.shed_deadline = 0
        self.shed_queue_full = 0
        #: routing decisions that walked past an SLO-breaching replica that
        #: pure load order would have picked (the observability→routing
        #: feedback loop, made observable itself)
        self.breach_avoided = 0

    @staticmethod
    def _prefix_tokens(prefix: Optional[Any]) -> "Optional[List[int]]":
        if prefix is None:
            return None
        tokens = getattr(prefix, "tokens", prefix)  # PrefixCache or raw ids
        if tokens is None:
            raise ValueError(
                "a shared prefix for a ReplicaSet needs its token ids (build it with "
                "cache_prefix(...) or pass the ids directly); hand-built PrefixCaches "
                "cannot be re-prefilled per replica"
            )
        return [int(t) for t in tokens]

    # ------------------------------------------------------------------ construction

    @classmethod
    def build(
        cls,
        module: Any,
        params: Any,
        config: Any,
        *,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        quantize: Optional[str] = None,
        replicas: Optional[int] = None,
        **engine_kwargs: Any,
    ) -> "ReplicaSet":
        """Build per-replica Generators and engines from one set of weights.

        With a dp>1 ``mesh``, the replica count is the mesh's data-parallel
        extent (``replicas`` may restate it but not change it) and each replica
        owns one TP submesh from :func:`slice_mesh`. Without one (``mesh`` is
        ``None`` or TP-only), ``replicas`` (default: the ``serve --dp-replicas``
        export, else 1) engines are placed round-robin over the visible devices
        — each replica gets its own single-device mesh, so N chips serve N
        independent decode loops from one process.
        """
        from unionml_tpu.models.generate import Generator

        if replicas is None:
            replicas = serve_dp_replicas() or None
        if mesh is not None and dp_extent(mesh) > 1:
            submeshes = slice_mesh(mesh, replicas)
        elif replicas is None or replicas == 1:
            submeshes = [mesh]
        elif mesh is not None:
            # a TP-only mesh replicated N times shares its device set — the
            # engines time-slice the same chips. Legitimate when serving is
            # host-dispatch-bound, surprising otherwise; say so once.
            logger.warning(
                f"ReplicaSet.build: {replicas} replicas over one TP-only mesh share "
                "its devices (time-sliced); add a data axis to give each replica its own chips"
            )
            submeshes = [mesh] * replicas
        else:
            submeshes = cls._single_device_meshes(replicas)
        generators = [
            Generator(module, params, config, mesh=sm, partition_rules=partition_rules, quantize=quantize)
            for sm in submeshes
        ]
        return cls(generators, **engine_kwargs)

    @staticmethod
    def _single_device_meshes(replicas: int) -> "List[Any]":
        """One full-axis-set 1-device mesh per replica, round-robin over the
        visible devices (the :func:`single_device_mesh` shape, one per chip)."""
        import jax
        from jax.sharding import Mesh

        from unionml_tpu.parallel.mesh import AXIS_ORDER

        devices = list(jax.devices())
        if replicas > len(devices):
            logger.warning(
                f"ReplicaSet: {replicas} replicas over {len(devices)} devices — replicas "
                "beyond the device count time-slice chips round-robin"
            )
        shape = (1,) * len(AXIS_ORDER)
        return [
            Mesh(np.asarray([devices[i % len(devices)]]).reshape(shape), AXIS_ORDER)
            for i in range(replicas)
        ]

    @classmethod
    def from_generator(
        cls, generator: Any, *, replicas: Optional[int] = None, **engine_kwargs: Any
    ) -> "ReplicaSet":
        """Re-host an existing Generator's weights as a replica set (the
        ``ContinuousBatcher`` delegation path). Params are re-placed onto each
        submesh — an fsdp-sharded tree is gathered per replica, paid once at
        construction. A pre-QUANTIZED Generator (``quantize="int8"``, by kwarg
        or the serve-wide ``UNIONML_TPU_QUANTIZE`` export) replicates too: its
        int8 tree is dequantized back to the param dtype once here and each
        replica re-quantizes its own placement — symmetric per-channel int8 is
        an exact round trip (dequantize then quantize reproduces the identical
        ``q``/``scale`` planes), so every replica serves bit-identical weights
        to the original engine."""
        params = generator.params
        quantize = getattr(generator, "quantize", None)
        if quantize is not None:
            from unionml_tpu.ops.quant import dequantize_tree

            mcfg = getattr(generator.module, "config", None)
            param_dtype = getattr(mcfg, "param_dtype", None) or getattr(mcfg, "dtype", None)
            params = dequantize_tree(params, dtype=param_dtype or "float32")
        return cls.build(
            generator.module,
            params,
            generator.config,
            mesh=generator.mesh,
            partition_rules=getattr(generator, "partition_rules", None),
            quantize=quantize,
            replicas=replicas,
            **engine_kwargs,
        )

    # ------------------------------------------------------------------ public API

    @property
    def replicas(self) -> int:
        return len(self._batchers)

    @property
    def batchers(self) -> "Tuple[Any, ...]":
        """The per-replica engines (read-only view; benchmarks introspect it)."""
        return tuple(self._batchers)

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        constraint: Optional[int] = None,
        deadline: Optional[float] = None,
    ) -> "Iterator[np.ndarray]":
        """Route a prompt to the least-loaded replica (prefix affinity
        permitting) and return its engine's token stream. Sheds with
        :class:`DeadlineExceeded` if the deadline already expired, and with
        :class:`QueueFullError` only when every replica's waiting queue is
        full — the scheduler's order is walked so one full replica never turns
        away work its siblings could take."""
        req_trace = current_trace()
        if expired(deadline):
            with self._lock:
                self.shed_deadline += 1
            if req_trace is not None:
                req_trace.event("engine.shed_deadline", phase="routing")
            raise DeadlineExceeded("deadline expired before the prompt was routed to a replica")
        loads = [batcher.load() for batcher in self._batchers]
        # actual per-replica cached-prefix lengths (the radix-tree probe) when
        # any engine runs a prefix cache; None keeps the LRU token-key fallback
        cached = None
        if any(getattr(b, "_radix", None) is not None for b in self._batchers):
            cached = [
                int(getattr(b, "cached_prefix_tokens", lambda _p: 0)(prompt))
                for b in self._batchers
            ]
        # per-replica SLO breach flags (cached health evaluations — cheap per
        # decision): a breaching replica is routed around, not routed to
        breaching = None
        if any(callable(getattr(b, "health", None)) for b in self._batchers):
            breaching = [
                callable(getattr(b, "health", None)) and b.health().get("state") == "breach"
                for b in self._batchers
            ]
        order, affinity_head = self._scheduler.order(loads, prompt, cached, breaching)
        if breaching is not None and any(breaching):
            # pure load order would have picked this replica; health demoted it
            pure_head = min(range(len(loads)), key=lambda i: (loads[i], i))
            if breaching[pure_head] and order and order[0] != pure_head:
                with self._lock:
                    self.breach_avoided += 1
        last_exc: Optional[QueueFullError] = None
        for replica in order:
            if req_trace is not None:
                # which replica, and the load it saw — recorded per ATTEMPT, so
                # a full replica's fall-through is visible on the timeline
                req_trace.event(
                    "engine.routed", replica=replica, load=round(loads[replica], 3),
                    affinity=affinity_head and replica == order[0],
                    breaching=bool(breaching[replica]) if breaching is not None else False,
                )
            try:
                stream = self._batchers[replica].submit(
                    prompt, max_new_tokens=max_new_tokens, constraint=constraint, deadline=deadline
                )
            except QueueFullError as exc:
                last_exc = exc
                continue
            self._scheduler.note(replica, prompt, affinity=affinity_head and replica == order[0])
            return stream
        with self._lock:
            self.shed_queue_full += 1
        if req_trace is not None:
            req_trace.event("engine.shed_queue_full", replicas=len(self._batchers))
        raise QueueFullError(
            f"all {len(self._batchers)} replicas' waiting queues are full"
        ) from last_exc

    def warmup(self) -> None:
        """AOT-compile every replica's admission/prefill/decode programs,
        concurrently — replicas own disjoint engines (and usually disjoint
        devices), so their compile walls overlap instead of stacking."""
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(self._batchers)) as pool:
            # list() propagates the first failure instead of dropping it
            list(pool.map(lambda batcher: batcher.warmup(), self._batchers))

    def load(self) -> float:
        """Aggregate token-weighted load (the signal a layer above a fleet of
        ReplicaSets would schedule on, mirroring the engine's own)."""
        return sum(batcher.load() for batcher in self._batchers)

    def health(self) -> Dict[str, Any]:
        """Fleet health (observability/health.py): mean + worst per-replica
        scores and the worst SLO state — the ``GET /healthz`` body."""
        from unionml_tpu.observability.health import fleet_health

        return fleet_health(self)

    def configure_slo(self, config: Any, replica: Optional[int] = None) -> None:
        """Swap SLO targets on every replica (or just ``replica`` — per-role
        targets for heterogeneous fleets) at runtime."""
        targets = self._batchers if replica is None else [self._batchers[replica]]
        for batcher in targets:
            batcher.configure_slo(config)

    def queued_prefill_tokens(self) -> int:
        """Fleet-wide prefill backlog in tokens (engines that predate the
        token accounting report 0)."""
        return sum(
            int(getattr(batcher, "queued_prefill_tokens", lambda: 0)())
            for batcher in self._batchers
        )

    def replica_loads(self) -> "List[Dict[str, Any]]":
        """Per-replica occupancy for live gauges: cheap (no full stats dict),
        evaluated at ``/metrics`` snapshot time."""
        out = []
        for i, batcher in enumerate(self._batchers):
            resident, waiting = batcher.occupancy()
            out.append(
                {
                    "replica": i,
                    "resident": resident,
                    "waiting": waiting,
                    "free_slots": max(int(getattr(batcher, "slots", 0)) - resident, 0),
                    "prefill_backlog_tokens": int(
                        getattr(batcher, "queued_prefill_tokens", lambda: 0)()
                    ),
                    "shed_queue_full": getattr(batcher, "shed_queue_full", 0),
                    "shed_deadline": getattr(batcher, "shed_deadline", 0),
                }
            )
        return out

    def stats(self) -> Dict[str, Any]:
        """Fleet snapshot for ``/metrics``: aggregates plus per-replica engine
        stats and the scheduler's routing telemetry."""
        per_replica = [batcher.stats() for batcher in self._batchers]

        def total(key: str) -> int:
            return sum(int(entry.get(key) or 0) for entry in per_replica)

        with self._lock:
            shed_deadline, shed_queue_full = self.shed_deadline, self.shed_queue_full
            breach_avoided = self.breach_avoided
        # fleet health headline (per-replica detail rides per_replica's own
        # rates/slo sections): strip the replicas list — stats() must not
        # duplicate every engine's health payload
        fleet = {
            key: value
            for key, value in self.health().items()
            if key != "replicas"
        }
        def total_prefill(key: str) -> int:
            return sum(
                int((entry.get("prefill") or {}).get(key) or 0) for entry in per_replica
            )

        return {
            "replicas": len(self._batchers),
            "scheduler": self._scheduler.stats(),
            "slots": total("slots"),
            "resident": total("resident"),
            "waiting": total("waiting"),
            "admitting": total("admitting"),
            "decode_dispatches": total("decode_dispatches"),
            "decoded_rows": total("decoded_rows"),
            # stall-free admission, fleet-wide: chunk counters + the token
            # backlog the token-weighted routing acts on (per-replica TTFT/TBT
            # percentiles stay under per_replica — percentiles don't sum)
            "prefill_chunks": total_prefill("chunks"),
            "prefill_backlog_tokens": total_prefill("backlog_tokens"),
            # fleet-wide radix prefix-cache totals (present only when at least
            # one replica runs the cache, so cache-off fleets keep today's
            # stats byte-for-byte; per-replica detail stays under per_replica)
            **(
                {
                    "prefix_cache": {
                        key: sum(
                            int((entry.get("prefix_cache") or {}).get(key) or 0)
                            for entry in per_replica
                        )
                        for key in ("hits", "misses", "tokens_avoided", "evictions",
                                    "cow_copies", "cached_blocks", "cached_bytes",
                                    "pinned_blocks")
                    }
                }
                if any("prefix_cache" in entry for entry in per_replica)
                else {}
            ),
            # fleet-level sheds (all replicas full / expired before routing) on
            # top of each engine's own counters
            "shed_queue_full": shed_queue_full + total("shed_queue_full"),
            "shed_deadline": shed_deadline + total("shed_deadline"),
            # fleet health score/state + how often routing walked around a
            # breaching replica (the observability→routing feedback, observable)
            "health": fleet,
            "breach_avoided": breach_avoided,
            "per_replica": per_replica,
        }

    def close(self, wait: bool = True, timeout: float = 120.0) -> None:
        """Drain every replica: stop admissions fleet-wide first (no stragglers
        re-routed into a replica that is about to close), then wait out the
        drains under one shared timeout."""
        for batcher in self._batchers:
            batcher.close(wait=False)
        if wait:
            deadline = time.monotonic() + timeout
            for batcher in self._batchers:
                batcher.close(wait=True, timeout=max(deadline - time.monotonic(), 0.0))
