"""TPU trainer compilation + execution driver."""

from unionml_tpu.train.driver import FitResult, TrainerConfig, evaluate, fit, make_train_step  # noqa: F401
