"""The pjit train-step driver: compile a (state, batch) -> (state, metrics) step over a
named mesh and run the donate-and-loop epoch schedule.

This layer is what the reference outsources wholesale to the user's ML framework inside
a Flyte task (reference unionml/model.py:425-440 simply calls
``self._trainer(model_object, *train_data)`` once, eagerly). Here the contract is
step-based so the whole hot loop is XLA:

- The user (or a model-library preset) supplies ``step_fn(state, batch) -> (state,
  metrics)``; :func:`make_train_step` builds the canonical one from a loss function.
- :func:`fit` constructs the mesh, resolves parameter shardings (explicit TP rules +
  inferred FSDP, :mod:`unionml_tpu.parallel.sharding`), compiles the step with
  ``jax.jit(donate_argnums=0, in_shardings=..., out_shardings=...)``, and loops over a
  host->HBM prefetch iterator. Buffer donation means the optimizer update is in-place
  in HBM; XLA inserts all the DP/FSDP collectives implied by the shardings.

Auxiliary subsystems the reference lacks (SURVEY.md §5): per-step profiler annotations,
step-level orbax checkpointing with resume, NaN guards, and a throughput metrics sink.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from unionml_tpu._logging import logger
from unionml_tpu.parallel.mesh import MeshSpec
from unionml_tpu.parallel.sharding import (
    PartitionRules,
    batch_sharding,
    combine_fsdp_tp,
    shard_pytree,
    unbox_partitioned,
)


@dataclasses.dataclass
class TrainerConfig:
    """Execution config attached to a step-mode ``@model.trainer``.

    This is the TPU analog of the reference's per-task kwargs (``requests``/``limits``
    resources, unionml/model.py:227) — but instead of k8s pod sizes it declares the
    compilation/measurement envelope of the training loop.
    """

    epochs: int = 1
    batch_size: int = 32
    mesh: Optional[MeshSpec] = None
    partition_rules: Optional[PartitionRules] = None
    #: t5x-style (logical_name, mesh_axis) pairs resolving flax
    #: ``nn.with_partitioning`` metadata; None = Partitioned names ARE mesh axes
    logical_axis_rules: "Optional[Sequence[Tuple[str, Any]]]" = None
    fsdp_min_weight_size: int = 2**14
    grad_accum_steps: int = 1
    donate: bool = True
    shuffle: bool = True
    seed: int = 0
    drop_remainder: bool = True
    prefetch: int = 2
    shard_batch_by_process: bool = False
    #: keep the whole split resident in HBM and gather batches on-device by index —
    #: per-step host->device traffic drops to the index vector (right for datasets
    #: that fit in HBM; essential when the host link is high-latency)
    device_data: bool = False
    #: with device_data, run this many optimizer steps per compiled dispatch via
    #: lax.scan — amortizes host round-trip latency over K steps
    steps_per_call: int = 1
    # checkpoint / resume (step-level; the reference only has final-artifact save)
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 0
    max_checkpoints_to_keep: int = 3
    resume: bool = False
    # observability
    log_every_steps: int = 0
    profile_dir: Optional[str] = None
    profile_steps: Tuple[int, int] = (10, 15)
    # debug: the TPU analog of a race detector is donation/NaN misuse (SURVEY.md §5.2)
    debug_nans: bool = False
    debug_disable_donation: bool = False


@dataclasses.dataclass
class FitResult:
    state: Any
    history: List[Dict[str, float]]
    steps: int
    samples_per_sec: float
    samples_per_sec_per_chip: float
    compile_time_s: float
    #: per-device HBM accounting after the final step (SURVEY.md §5.5 metrics
    #: sink commitment): ``{"bytes_in_use": ..., "peak_bytes_in_use": ...}`` from
    #: device 0, or None when the backend exposes no memory stats (CPU)
    memory_stats: Optional[Dict[str, int]] = None


def _device_memory_stats() -> Optional[Dict[str, int]]:
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit", "largest_alloc_size")
    filtered = {k: int(v) for k, v in stats.items() if k in keep}
    return filtered or None  # a stats dict without byte counters is as good as none


def make_train_step(
    loss_fn: Callable[..., Any],
    *,
    has_aux: bool = False,
    grad_accum_steps: int = 1,
    remat: bool = False,
) -> Callable[[Any, Any], Tuple[Any, Dict[str, jax.Array]]]:
    """Build the canonical ``(state, batch) -> (state, metrics)`` step from a loss fn.

    ``loss_fn(params, batch, rngs...)`` -> loss (or ``(loss, aux_dict)`` with
    ``has_aux=True``). ``state`` must expose ``params`` and ``apply_gradients`` (the
    flax ``TrainState`` protocol). Gradient accumulation runs microbatches under
    ``lax.scan`` so the unrolled loop stays a single XLA computation; ``remat``
    checkpoints the loss computation to trade FLOPs for HBM.
    """
    base_loss = jax.checkpoint(loss_fn) if remat else loss_fn
    grad_fn = jax.value_and_grad(base_loss, has_aux=has_aux)

    def single_step(state: Any, batch: Any) -> Tuple[Any, Dict[str, jax.Array]]:
        if has_aux:
            (loss, aux), grads = grad_fn(state.params, batch)
        else:
            loss, grads = grad_fn(state.params, batch)
            aux = {}
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, **aux}

    if grad_accum_steps <= 1:
        return single_step

    def accum_step(state: Any, batch: Any) -> Tuple[Any, Dict[str, jax.Array]]:
        # Shardings pinned by fit() (see _pin_accum_shardings): the scan carry
        # follows the param layout and the reshaped microbatch stack keeps the
        # batch layout, instead of leaving both to partitioner inference. The
        # round-4 "Involuntary full rematerialization" in this loop turned out
        # to be the embed scatter-add (fixed at its root in layers.IotaEmbed);
        # the pins make the intended layouts explicit so a future inference
        # change cannot silently reintroduce a per-microbatch reshard — the
        # dryrun asserts the SPMD log stays warning-free either way.
        param_sh, micro_sh, micro_div = accum_step.pinned_shardings

        def pin_grads(tree: Any) -> Any:
            if param_sh is None:
                return tree
            return jax.lax.with_sharding_constraint(tree, param_sh)

        def split(leaf: jax.Array) -> jax.Array:
            b = leaf.shape[0]
            micro = leaf.reshape((grad_accum_steps, b // grad_accum_steps) + leaf.shape[1:])
            # pin only when the microbatch dim divides evenly over the batch
            # axes — the indivisible-final-batch fallback arrives replicated
            if micro_sh is not None and micro.shape[1] % micro_div == 0:
                micro = jax.lax.with_sharding_constraint(micro, micro_sh)
            return micro

        microbatches = jax.tree_util.tree_map(split, batch)

        def body(carry, microbatch):
            grads_acc, loss_acc = carry
            if has_aux:
                (loss, aux), grads = grad_fn(state.params, microbatch)
            else:
                loss, grads = grad_fn(state.params, microbatch)
                aux = {}
            grads_acc = pin_grads(jax.tree_util.tree_map(jnp.add, grads_acc, grads))
            return (grads_acc, loss_acc + loss), aux

        zeros = pin_grads(jax.tree_util.tree_map(jnp.zeros_like, state.params))
        (grads, loss_sum), aux_stacked = jax.lax.scan(body, (zeros, jnp.zeros(())), microbatches)
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum_steps, grads)
        new_state = state.apply_gradients(grads=grads)
        aux_mean = jax.tree_util.tree_map(lambda a: a.mean(axis=0), aux_stacked)
        return new_state, {"loss": loss_sum / grad_accum_steps, **aux_mean}

    accum_step.pinned_shardings = (None, None, 1)
    return accum_step


def _pin_accum_shardings(step_fn: Any, state_shardings: Any, mesh) -> None:
    """If ``step_fn`` is a grad-accumulation step from :func:`make_train_step`,
    pin its scan-carry gradient shardings to the param shardings and its
    microbatch stack to ``P(None, *batch_spec)`` so the partitioner cannot
    choose a conflicting layout inside the scan (re-read at each trace, so one
    step_fn reused across fits on different meshes re-pins correctly)."""
    if not hasattr(step_fn, "pinned_shardings"):
        return
    try:
        param_sh = state_shardings.params
    except AttributeError:  # state without a .params subtree: skip the carry pin
        param_sh = None
    from unionml_tpu.parallel.sharding import batch_axis_size

    batch_sh = batch_sharding(mesh)
    micro_spec = jax.sharding.PartitionSpec(None, *batch_sh.spec)
    micro_sh = jax.sharding.NamedSharding(mesh, micro_spec)
    step_fn.pinned_shardings = (param_sh, micro_sh, batch_axis_size(mesh))


def _sync_fence(tree: Any) -> None:
    """Force a real device-queue sync by fetching one element to the host.

    ``jax.block_until_ready`` is unreliable on some experimental PJRT plugins (it can
    return while work is still queued); a literal transfer cannot lie.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return
    leaf = leaves[0]
    try:
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            # a multi-process global array is not fully addressable — fetch from
            # a local shard instead of the (possibly remote) global index 0
            local = shards[0].data
            np.asarray(local if getattr(local, "ndim", 0) == 0 else local.ravel()[0])
        else:
            np.asarray(leaf if getattr(leaf, "ndim", 0) == 0 else leaf.ravel()[0])
    except Exception:
        jax.block_until_ready(leaf)


def _tree_device_shardings(state: Any, mesh, rules: Optional[PartitionRules], min_weight: int, logical_rules=None):
    return combine_fsdp_tp(state, mesh, rules, min_weight_size=min_weight, logical_rules=logical_rules)


def _make_checkpoint_manager(config: TrainerConfig):
    if not config.checkpoint_dir or config.checkpoint_every_steps <= 0:
        return None
    import orbax.checkpoint as ocp

    options = ocp.CheckpointManagerOptions(
        max_to_keep=config.max_checkpoints_to_keep,
        enable_async_checkpointing=True,
    )
    return ocp.CheckpointManager(config.checkpoint_dir, options=options)


def fit(
    state: Any,
    step_fn: Callable[[Any, Any], Tuple[Any, Dict[str, jax.Array]]],
    data: Any,
    config: TrainerConfig,
) -> FitResult:
    """Compile ``step_fn`` over the configured mesh and run the training loop.

    :param state: initial train state pytree (e.g. ``flax.training.train_state.TrainState``).
    :param data: per-split data list (``[features, targets, ...]``) from
        :meth:`unionml_tpu.dataset.Dataset.get_data`, or any pytree of arrays with a
        shared leading sample dim.
    """
    from unionml_tpu.data.pipeline import PrefetchIterator

    mesh = (config.mesh or MeshSpec()).build()
    n_chips = mesh.size

    with mesh:
        state_shardings = _tree_device_shardings(
            state, mesh, config.partition_rules, config.fsdp_min_weight_size, config.logical_axis_rules
        )
        # flax nn.with_partitioning metadata has been consumed into the shardings;
        # train on the raw value tree
        state = unbox_partitioned(state)
        state = shard_pytree(state, state_shardings)
        batch_sh = batch_sharding(mesh)
        _pin_accum_shardings(step_fn, state_shardings, mesh)

        donate = (0,) if (config.donate and not config.debug_disable_donation) else ()
        # batch in_sharding is left unconstrained: batches arrive pre-placed by the
        # prefetch iterator (data-axis sharded normally, replicated for indivisible
        # final partial batches), and constraining it here would reject the fallback
        compiled_step = jax.jit(
            step_fn,
            donate_argnums=donate,
            in_shardings=(state_shardings, None),
            out_shardings=(state_shardings, None),
        )

        manager = _make_checkpoint_manager(config)
        start_step = 0
        if manager is not None and config.resume:
            latest = manager.latest_step()
            if latest is not None:
                import orbax.checkpoint as ocp

                abstract = jax.tree_util.tree_map(
                    lambda x, s: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x), sharding=s),
                    state,
                    state_shardings,
                )
                state = manager.restore(latest, args=ocp.args.StandardRestore(abstract))
                start_step = latest
                logger.info(f"resumed train state from checkpoint step {latest}")

        if config.device_data:
            if jax.process_count() > 1:
                # Multi-process device_data: every process computes the same host
                # data (seeded readers — the multi-host contract), and
                # place_global_array materializes only this process's addressable
                # row-shards, so per-process HBM holds 1/process_count of the
                # dataset. The epoch permute and dynamic_slice batch selection run
                # inside jit over the global array — SPMD, XLA inserts the
                # resharding collectives. shard_batch_by_process is therefore
                # implied (the global array IS process-sharded); the flag only
                # changes the host-batching path.
                logger.info(
                    f"device_data over {jax.process_count()} processes: dataset "
                    "globally sharded, per-process HBM holds its row-shards only"
                )
            if not config.drop_remainder:
                logger.info(
                    "device_data mode always drops the partial final batch (fixed-shape "
                    "dynamic_slice); drop_remainder=False is ignored"
                )
            # whole split resident in HBM; per-step H2D traffic = the index vector only
            source = PrefetchIterator(
                data,
                batch_size=config.batch_size,
                sharding=None,
                drop_remainder=True,  # fixed-shape dynamic_slice; partials never scheduled
                shuffle=config.shuffle,
                seed=config.seed,
                prefetch=0,
                epochs=config.epochs,
                skip_batches=start_step,
            )
            host_tree = jax.tree_util.tree_unflatten(source._treedef, source._leaves)
            from unionml_tpu.parallel.sharding import place_global_array

            try:
                data_dev = jax.tree_util.tree_map(lambda leaf: place_global_array(leaf, batch_sh), host_tree)
            except Exception:
                data_dev = jax.device_put(host_tree)
            _sync_fence(data_dev)  # keep the (possibly multi-second) H2D out of the timed loop

            # shuffling = ONE on-device permutation per epoch; batches are then
            # contiguous dynamic slices — ~2 orders of magnitude faster than a
            # per-step arbitrary-index gather over the full table
            permute = jax.jit(
                lambda dataset, perm: jax.tree_util.tree_map(lambda leaf: jnp.take(leaf, perm, axis=0), dataset)
            )

            def slice_scan_step(state: Any, dataset: Any, starts: jax.Array):
                # starts: [K] — K optimizer steps in one dispatch; lax.scan keeps it a
                # single XLA computation, so host round-trip cost is paid once per K
                def body(st, start):
                    batch = jax.tree_util.tree_map(
                        lambda leaf: jax.lax.dynamic_slice_in_dim(leaf, start, config.batch_size, 0), dataset
                    )
                    return step_fn(st, batch)

                state, metrics_seq = jax.lax.scan(body, state, starts)
                return state, jax.tree_util.tree_map(lambda m: m[-1], metrics_seq)

            compiled_gather = jax.jit(
                slice_scan_step,
                donate_argnums=donate,
                in_shardings=(state_shardings, None, None),
                out_shardings=(state_shardings, None),
            )

            steps_per_call = max(1, min(config.steps_per_call, source.steps_per_epoch() or 1))

            def payloads():
                current_epoch = -1
                epoch_data = data_dev
                group: List[int] = []

                def flush(epoch_data, group):
                    # partial trailing groups run as a smaller dispatch (one extra
                    # compile per distinct size) rather than being silently dropped
                    return (epoch_data, jnp.asarray(group, dtype=jnp.int32)), config.batch_size * len(group), len(
                        group
                    )

                # the schedule only emits full batches (the source is built with
                # drop_remainder=True, so steps_per_epoch floors)
                for epoch, lo, _size in source.contiguous_schedule():
                    if epoch != current_epoch:
                        if group:
                            yield flush(epoch_data, group)
                            group = []
                        # release the previous epoch's permuted copy BEFORE building the
                        # next one — together with the fit loop dropping its payload
                        # reference each step, peak HBM stays at 2x the dataset
                        # (base + one permuted copy), not 3x
                        epoch_data = None
                        epoch_data = (
                            permute(data_dev, jnp.asarray(source._epoch_order(epoch)))
                            if config.shuffle
                            else data_dev
                        )
                        current_epoch = epoch
                    group.append(lo)
                    if len(group) == steps_per_call:
                        yield flush(epoch_data, group)
                        group = []
                if group:
                    yield flush(epoch_data, group)

            def run_step(state: Any, payload: Any):
                epoch_data, starts = payload
                return compiled_gather(state, epoch_data, starts)

        else:
            iterator = PrefetchIterator(
                data,
                batch_size=config.batch_size,
                sharding=batch_sh,
                drop_remainder=config.drop_remainder,
                shuffle=config.shuffle,
                seed=config.seed,
                prefetch=config.prefetch,
                shard_by_process=config.shard_batch_by_process,
                epochs=config.epochs,
                skip_batches=start_step,  # resume reproduces the seeded schedule, minus consumed batches
            )

            def payloads():
                for batch in iterator:
                    yield batch, int(jax.tree_util.tree_leaves(batch)[0].shape[0]), 1

            def run_step(state: Any, payload: Any):
                return compiled_step(state, payload)

        history: List[Dict[str, float]] = []
        step_idx = start_step  # number of completed optimizer steps
        compile_time = 0.0
        samples_seen = 0
        first_batch_samples = 0
        loop_start: Optional[float] = None
        last_metrics: Any = None
        trace_active = False

        # XLA:CPU emulated-mesh collectives run an in-process rendezvous across one
        # thread per "device"; with async dispatch piling up executions on a small
        # host (this box: nproc=1), participants starve past the 40 s rendezvous
        # termination timeout and the runtime hard-aborts the process. Serialize
        # dispatch there — a per-step fence costs nothing on an already-CPU-bound
        # test backend. Real TPU keeps the async pipeline.
        serialize_dispatch = jax.default_backend() == "cpu" and mesh.size > 1

        prev_debug_nans = jax.config.jax_debug_nans
        if config.debug_nans:
            jax.config.update("jax_debug_nans", True)
        try:
            for payload, batch_n, steps_in_payload in payloads():
                # triggers use crossing semantics: step_idx may advance in strides of
                # steps_per_call, so equality / modulo tests would silently never fire
                if config.profile_dir and not trace_active and step_idx >= config.profile_steps[0]:
                    jax.profiler.start_trace(config.profile_dir)
                    trace_active = True
                with jax.profiler.TraceAnnotation("unionml_tpu.train_step"):
                    if loop_start is None:
                        t0 = time.perf_counter()
                        state, last_metrics = run_step(state, payload)
                        _sync_fence(last_metrics)
                        compile_time = time.perf_counter() - t0
                        loop_start = time.perf_counter()
                        first_batch_samples = batch_n
                    else:
                        state, last_metrics = run_step(state, payload)
                        if serialize_dispatch:
                            _sync_fence(last_metrics)
                # drop the payload reference before the generator's next epoch-boundary
                # permute runs — otherwise the old permuted copy stays live and peak
                # HBM hits 3x the dataset in device_data mode
                payload = None
                prev_step = step_idx
                step_idx += steps_in_payload
                samples_seen += batch_n
                if config.log_every_steps and (
                    step_idx // config.log_every_steps > prev_step // config.log_every_steps
                ):
                    host_metrics = {k: float(v) for k, v in last_metrics.items()}
                    history.append({"step": step_idx, **host_metrics})
                    logger.info(f"step {step_idx}: {host_metrics}")
                if manager is not None and config.checkpoint_every_steps and (
                    step_idx // config.checkpoint_every_steps > prev_step // config.checkpoint_every_steps
                ):
                    import orbax.checkpoint as ocp

                    manager.save(step_idx, args=ocp.args.StandardSave(state))
                if config.profile_dir and trace_active and step_idx > config.profile_steps[1]:
                    jax.profiler.stop_trace()
                    trace_active = False
        finally:
            if trace_active:
                jax.profiler.stop_trace()
            if config.debug_nans:
                jax.config.update("jax_debug_nans", prev_debug_nans)

        if last_metrics is not None:
            _sync_fence(last_metrics)
            host_metrics = {k: float(v) for k, v in last_metrics.items()}
            if not history or history[-1].get("step") != step_idx:
                history.append({"step": step_idx, **host_metrics})

        if manager is not None:
            import orbax.checkpoint as ocp

            if manager.latest_step() != step_idx:
                manager.save(step_idx, args=ocp.args.StandardSave(state), force=True)
            manager.wait_until_finished()

        post_compile_samples = samples_seen - first_batch_samples
        elapsed = (time.perf_counter() - loop_start) if loop_start is not None else 0.0
        sps = post_compile_samples / elapsed if elapsed > 0 and post_compile_samples > 0 else 0.0

    return FitResult(
        state=state,
        history=history,
        steps=step_idx - start_step,
        samples_per_sec=sps,
        samples_per_sec_per_chip=sps / max(n_chips, 1),
        compile_time_s=compile_time,
        memory_stats=_device_memory_stats(),
    )


def evaluate(
    state: Any,
    eval_step: Callable[[Any, Any], Dict[str, jax.Array]],
    data: Any,
    *,
    batch_size: int = 128,
    mesh: Optional[MeshSpec] = None,
    partition_rules: Optional[PartitionRules] = None,
    fsdp_min_weight_size: int = 2**14,
    logical_axis_rules: "Optional[Sequence[Tuple[str, Any]]]" = None,
) -> Dict[str, float]:
    """Run a jitted eval step over a split and average the metrics.

    A state leaf that already lives on an equal mesh keeps its placement (the
    state ``fit`` returns is consumed in place — no per-split reshard, even for
    layouts that came from since-unboxed ``nn.Partitioned`` metadata); host
    leaves are placed via the same resolution the train driver uses (logical
    metadata + explicit TP rules + inferred FSDP).
    """
    from jax.sharding import NamedSharding

    from unionml_tpu.data.pipeline import PrefetchIterator

    built = (mesh or MeshSpec()).build()
    with built:
        resolved = _tree_device_shardings(
            state, built, partition_rules, fsdp_min_weight_size, logical_axis_rules
        )
        state = unbox_partitioned(state)

        def keep_or_resolve(leaf: Any, fallback: Any) -> Any:
            existing = getattr(leaf, "sharding", None)
            if isinstance(existing, NamedSharding) and existing.mesh == built:
                return existing
            return fallback

        state_shardings = jax.tree_util.tree_map(keep_or_resolve, state, resolved)
        state = shard_pytree(state, state_shardings)
        batch_sh = batch_sharding(built)
        # batch in_sharding stays unconstrained: the final partial batch arrives
        # replicated when its size does not divide the data axis
        compiled = jax.jit(eval_step, in_shardings=(state_shardings, None))
        totals: Dict[str, float] = {}
        count = 0
        for batch in PrefetchIterator(data, batch_size=batch_size, sharding=batch_sh, drop_remainder=False):
            metrics = compiled(state, batch)
            n = jax.tree_util.tree_leaves(batch)[0].shape[0]
            for k, v in metrics.items():
                totals[k] = totals.get(k, 0.0) + float(v) * n
            count += n
    return {k: v / max(count, 1) for k, v in totals.items()}
