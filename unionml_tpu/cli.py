"""unionml-tpu command-line interface.

Parity surface: reference unionml/cli.py:26-212 — a typer app exposing ``init``,
``deploy``, ``train``, ``predict``, ``list-model-versions``, ``fetch-model`` and a
``serve`` command that boots the HTTP prediction service with ``--model-path``. typer
is not in the TPU image, so this is a plain ``click`` group with the same command
names, options, and behaviors; ``serve`` runs our self-contained asyncio server
(:mod:`unionml_tpu.serving.http`) instead of wrapping uvicorn (cli.py:172-205).
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Any, Optional

import click

from unionml_tpu.defaults import MODEL_PATH_ENV_VAR


@click.group(name="unionml-tpu")
@click.version_option(package_name="unionml-tpu", message="%(version)s")
def app() -> None:
    """unionml-tpu: deploy TPU-native machine learning microservices."""


def _locate_model(app_ref: str) -> Any:
    """Import ``module:variable`` and return the Model (reference remote.get_model)."""
    from unionml_tpu.resolver import locate

    sys.path.insert(0, os.getcwd())
    obj = locate(app_ref)
    return obj


def _parse_json_option(raw: Optional[str], option: str) -> Any:
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError as exc:
        raise click.BadParameter(f"{option} must be valid JSON: {exc}")


@app.command("init")
@click.argument("app_name")
@click.option(
    "--template",
    "-t",
    default="basic",
    show_default=True,
    help="template to scaffold the app from (see `unionml-tpu templates`)",
)
def init(app_name: str, template: str) -> None:
    """Initialize a new unionml-tpu project (reference cli.py:33-51)."""
    from unionml_tpu.templating import render_template

    try:
        project_dir = render_template(template, app_name, Path.cwd())
    except (ValueError, FileExistsError) as exc:
        raise click.ClickException(str(exc))
    click.echo(f"Created unionml-tpu project at {project_dir}")


@app.command("templates")
def templates() -> None:
    """List available project templates."""
    from unionml_tpu.templating import list_templates

    for name in list_templates():
        click.echo(name)


@app.command("deploy")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to the git HEAD sha")
@click.option("--allow-uncommitted", is_flag=True, default=False, help="deploy with uncommitted changes")
@click.option("--patch", is_flag=True, default=False, help="fast re-registration: re-ship source only")
def deploy(app_ref: str, app_version: Optional[str], allow_uncommitted: bool, patch: bool) -> None:
    """Deploy a model's train/predict services to the backend (reference cli.py:54-82)."""
    model = _locate_model(app_ref)
    version = model.remote_deploy(app_version=app_version, allow_uncommitted=allow_uncommitted, patch=patch)
    click.echo(f"Deployed {app_ref} version {version}")


@app.command("train")
@click.argument("app_ref", metavar="APP")
@click.option("--inputs", "-i", default=None, help="training inputs as a JSON object")
@click.option("--app-version", default=None, help="app version to run; defaults to latest deployed")
def train(app_ref: str, inputs: Optional[str], app_version: Optional[str]) -> None:
    """Train a model on the backend (reference cli.py:85-103)."""
    model = _locate_model(app_ref)
    parsed = _parse_json_option(inputs, "--inputs") or {}
    click.echo(f"Training {model.name}")
    model.remote_train(app_version=app_version, wait=True, **parsed)
    assert model.artifact is not None
    click.echo("Done.")
    click.echo(f"Model: {model.artifact.model_object}")
    click.echo(f"Metrics: {model.artifact.metrics}")


@app.command("predict")
@click.argument("app_ref", metavar="APP")
@click.option("--inputs", "-i", default=None, help="prediction inputs (reader kwargs) as a JSON object")
@click.option(
    "--features",
    "-f",
    default=None,
    type=click.Path(exists=True, dir_okay=False, path_type=Path),
    help="generate predictions from a JSON file of features",
)
@click.option("--app-version", default=None, help="app version to run; defaults to latest deployed")
@click.option("--model-version", default="latest", show_default=True, help="model version to predict with")
def predict(
    app_ref: str,
    inputs: Optional[str],
    features: Optional[Path],
    app_version: Optional[str],
    model_version: str,
) -> None:
    """Generate predictions on the backend (reference cli.py:106-127)."""
    model = _locate_model(app_ref)
    parsed_inputs = _parse_json_option(inputs, "--inputs") or {}
    parsed_features = json.loads(features.read_text()) if features is not None else None
    click.echo(f"Generating predictions with {model.name}")
    predictions = model.remote_predict(
        app_version=app_version,
        model_version=None if model_version == "latest" else model_version,
        wait=True,
        features=parsed_features,
        **parsed_inputs,
    )
    click.echo(f"Predictions: {predictions}")


@app.command("list-model-versions")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to latest deployed")
@click.option("--limit", default=10, show_default=True, help="maximum number of versions to list")
def list_model_versions(app_ref: str, app_version: Optional[str], limit: int) -> None:
    """List all trained model versions, newest first (reference cli.py:130-144)."""
    model = _locate_model(app_ref)
    app_version = app_version or model._backend.latest_app_version(model)
    click.echo(f"Listing model versions for app {app_ref} (app version: {app_version})")
    for version in model.remote_list_model_versions(app_version=app_version, limit=limit):
        click.echo(f"- {version}")


@app.command("fetch-model")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to latest deployed")
@click.option("--model-version", default="latest", show_default=True, help="model version to fetch")
@click.option(
    "--output-file",
    "-o",
    required=True,
    type=click.Path(dir_okay=False, path_type=Path),
    help="path to write the fetched model object to",
)
@click.option("--kwargs", default=None, help="JSON keyword arguments forwarded to the model saver")
def fetch_model(
    app_ref: str,
    app_version: Optional[str],
    model_version: str,
    output_file: Path,
    kwargs: Optional[str],
) -> None:
    """Fetch a trained model from the backend registry and save it locally
    (reference cli.py:147-164)."""
    model = _locate_model(app_ref)
    saver_kwargs = _parse_json_option(kwargs, "--kwargs") or {}
    model.artifact = model._backend.fetch_latest_artifact(
        model, app_version=app_version, model_version=model_version
    )
    model.save(output_file, **saver_kwargs)
    click.echo(f"Model saved to {output_file}")


@app.command("serve")
@click.argument("app_ref", metavar="APP")
@click.option("--model-path", default=None, type=click.Path(path_type=Path), help="path to the saved model object")
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8000, show_default=True, type=int)
@click.option("--remote", is_flag=True, default=False, help="load the model from the remote backend registry")
@click.option("--app-version", default=None, help="app version for --remote model loading")
@click.option("--model-version", default="latest", show_default=True, help="model version for --remote loading")
def serve(
    app_ref: str,
    model_path: Optional[Path],
    host: str,
    port: int,
    remote: bool,
    app_version: Optional[str],
    model_version: str,
) -> None:
    """Start the HTTP prediction service (reference cli.py:172-205).

    The reference clones uvicorn's CLI and injects ``--model-path`` via the
    ``UNIONML_MODEL_PATH`` env var, refusing to run when the variable is pre-set
    (cli.py:187-202); identical semantics here, on our own server.
    """
    if model_path is not None:
        if os.getenv(MODEL_PATH_ENV_VAR) is not None:
            raise click.ClickException(
                f"{MODEL_PATH_ENV_VAR} environment variable is already set, which takes precedence "
                "over the --model-path option. Unset it to use --model-path."
            )
        if not model_path.exists():
            raise click.ClickException(f"model path {model_path} does not exist")
        os.environ[MODEL_PATH_ENV_VAR] = str(model_path)

    target = _locate_model(app_ref)
    from unionml_tpu.serving import ServingApp

    if isinstance(target, ServingApp):
        serving = target
    else:
        serving = target.serve(remote=remote, app_version=app_version, model_version=model_version)
    serving.run(host=host, port=port)


def main() -> None:  # console-script entry point (reference setup.py:34)
    app()


if __name__ == "__main__":
    main()
