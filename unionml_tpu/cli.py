"""unionml-tpu command-line interface.

Parity surface: reference unionml/cli.py:26-212 — a typer app exposing ``init``,
``deploy``, ``train``, ``predict``, ``list-model-versions``, ``fetch-model`` and a
``serve`` command that boots the HTTP prediction service with ``--model-path``. typer
is not in the TPU image, so this is a plain ``click`` group with the same command
names, options, and behaviors; ``serve`` runs our self-contained asyncio server
(:mod:`unionml_tpu.serving.http`) instead of wrapping uvicorn (cli.py:172-205).
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Any, Optional

import click

from unionml_tpu.defaults import MODEL_PATH_ENV_VAR


@click.group(name="unionml-tpu")
@click.version_option(package_name="unionml-tpu", message="%(version)s")
def app() -> None:
    """unionml-tpu: deploy TPU-native machine learning microservices."""


def _locate_model(app_ref: str) -> Any:
    """Import ``module:variable`` and return the Model (reference remote.get_model)."""
    from unionml_tpu.resolver import locate

    sys.path.insert(0, os.getcwd())
    obj = locate(app_ref)
    return obj


def _parse_json_option(raw: Optional[str], option: str) -> Any:
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError as exc:
        raise click.BadParameter(f"{option} must be valid JSON: {exc}")


@app.command("init")
@click.argument("app_name")
@click.option(
    "--template",
    "-t",
    default="basic",
    show_default=True,
    help="template to scaffold the app from (see `unionml-tpu templates`)",
)
def init(app_name: str, template: str) -> None:
    """Initialize a new unionml-tpu project (reference cli.py:33-51)."""
    from unionml_tpu.templating import render_template

    try:
        project_dir = render_template(template, app_name, Path.cwd())
    except (ValueError, FileExistsError) as exc:
        raise click.ClickException(str(exc))
    click.echo(f"Created unionml-tpu project at {project_dir}")


@app.command("templates")
def templates() -> None:
    """List available project templates."""
    from unionml_tpu.templating import list_templates

    for name in list_templates():
        click.echo(name)


@app.command("lint")
@click.argument("paths", nargs=-1, metavar="[PATHS]...")
@click.option(
    "--format",
    "format_",
    type=click.Choice(["text", "json", "sarif"]),
    default="text",
    show_default=True,
    help="report format (json follows the stable schema docs/static-analysis.md describes; "
    "sarif emits SARIF 2.1.0 for CI/editor annotation surfaces)",
)
@click.option("--select", default=None, help="comma-separated rule ids to run (default: all)")
@click.option("--ignore", default=None, help="comma-separated rule ids to skip")
@click.option(
    "--show-suppressed",
    is_flag=True,
    default=False,
    help="also list findings silenced by `# tpu-lint: disable=RULE` comments",
)
@click.option(
    "--changed-only",
    is_flag=False,
    flag_value="HEAD",
    default=None,
    metavar="[REF]",
    help="report findings only for files changed vs REF (default HEAD) plus untracked "
    "files — the fast pre-push path; the whole-program index still covers all PATHS",
)
@click.option(
    "--baseline",
    default=None,
    metavar="FILE",
    help="JSON baseline of known findings: matched findings are reported as baselined "
    "(and do not fail the gate), only new ones count — composes with --changed-only "
    "and --format sarif (baselineState)",
)
@click.option(
    "--update-baseline",
    is_flag=True,
    default=False,
    help="record the run's findings to --baseline FILE (then report zero new)",
)
def lint(
    paths: "tuple[str, ...]",
    format_: str,
    select: Optional[str],
    ignore: Optional[str],
    show_suppressed: bool,
    changed_only: Optional[str],
    baseline: Optional[str],
    update_baseline: bool,
) -> None:
    """Run tpu-lint, the TPU/concurrency-aware static analyzer (TPU001-TPU019).

    Per-file rules check for host syncs inside jit-compiled functions,
    use-after-donate, unlocked mutation of lock-guarded state, blocking calls
    in serving handlers/engine loops, bare env-var numeric parses, wall-clock
    time.time() in duration/deadline arithmetic, *_locked helpers called
    without holding the lock, threads started in closeable classes but never
    joined, and unbounded per-key registries. Whole-program rules over the
    cross-module project index detect lock-order cycles (TPU010), recompile
    hazards at jit static positions (TPU011), and contextvar reads behind
    executor/thread hops without ctx.run (TPU012); TPU001/TPU002 follow jit
    reachability and donation across modules through the same index. A
    per-function CFG + dataflow layer adds the exception-path rules:
    resource leaks when a call raises between acquire and release (TPU016),
    tenant charges with no refund on the error path (TPU017), locks held
    across generator yields (TPU018), and early returns that skip a release
    (TPU019). PATHS
    defaults to ``unionml_tpu``; exits 0 when clean, 1 on findings, 2 on
    usage/parse errors. Also runnable as ``python -m unionml_tpu.analysis``.
    """
    from unionml_tpu.analysis.engine import main as lint_main

    argv = list(paths) + ["--format", format_]
    if select:
        argv += ["--select", select]
    if ignore:
        argv += ["--ignore", ignore]
    if show_suppressed:
        argv.append("--show-suppressed")
    if changed_only:
        argv += ["--changed-only", changed_only]
    if baseline:
        argv += ["--baseline", baseline]
    if update_baseline:
        argv.append("--update-baseline")
    sys.exit(lint_main(argv))


@app.command("deploy")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to the git HEAD sha")
@click.option("--allow-uncommitted", is_flag=True, default=False, help="deploy with uncommitted changes")
@click.option("--patch", is_flag=True, default=False, help="fast re-registration: re-ship source only")
def deploy(app_ref: str, app_version: Optional[str], allow_uncommitted: bool, patch: bool) -> None:
    """Deploy a model's train/predict services to the backend (reference cli.py:54-82)."""
    model = _locate_model(app_ref)
    version = model.remote_deploy(app_version=app_version, allow_uncommitted=allow_uncommitted, patch=patch)
    click.echo(f"Deployed {app_ref} version {version}")


@app.command("train")
@click.argument("app_ref", metavar="APP")
@click.option("--inputs", "-i", default=None, help="training inputs as a JSON object")
@click.option("--app-version", default=None, help="app version to run; defaults to latest deployed")
def train(app_ref: str, inputs: Optional[str], app_version: Optional[str]) -> None:
    """Train a model on the backend (reference cli.py:85-103)."""
    model = _locate_model(app_ref)
    parsed = _parse_json_option(inputs, "--inputs") or {}
    click.echo(f"Training {model.name}")
    model.remote_train(app_version=app_version, wait=True, **parsed)
    assert model.artifact is not None
    click.echo("Done.")
    click.echo(f"Model: {model.artifact.model_object}")
    click.echo(f"Metrics: {model.artifact.metrics}")


@app.command("predict")
@click.argument("app_ref", metavar="APP")
@click.option("--inputs", "-i", default=None, help="prediction inputs (reader kwargs) as a JSON object")
@click.option(
    "--features",
    "-f",
    default=None,
    type=click.Path(exists=True, dir_okay=False, path_type=Path),
    help="generate predictions from a JSON file of features",
)
@click.option("--app-version", default=None, help="app version to run; defaults to latest deployed")
@click.option("--model-version", default="latest", show_default=True, help="model version to predict with")
def predict(
    app_ref: str,
    inputs: Optional[str],
    features: Optional[Path],
    app_version: Optional[str],
    model_version: str,
) -> None:
    """Generate predictions on the backend (reference cli.py:106-127)."""
    model = _locate_model(app_ref)
    parsed_inputs = _parse_json_option(inputs, "--inputs") or {}
    parsed_features = json.loads(features.read_text()) if features is not None else None
    click.echo(f"Generating predictions with {model.name}")
    predictions = model.remote_predict(
        app_version=app_version,
        model_version=None if model_version == "latest" else model_version,
        wait=True,
        features=parsed_features,
        **parsed_inputs,
    )
    click.echo(f"Predictions: {predictions}")


@app.command("list-model-versions")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to latest deployed")
@click.option("--limit", default=10, show_default=True, help="maximum number of versions to list")
def list_model_versions(app_ref: str, app_version: Optional[str], limit: int) -> None:
    """List all trained model versions, newest first (reference cli.py:130-144)."""
    model = _locate_model(app_ref)
    app_version = app_version or model._backend.latest_app_version(model)
    click.echo(f"Listing model versions for app {app_ref} (app version: {app_version})")
    for version in model.remote_list_model_versions(app_version=app_version, limit=limit):
        click.echo(f"- {version}")


@app.command("fetch-model")
@click.argument("app_ref", metavar="APP")
@click.option("--app-version", default=None, help="app version; defaults to latest deployed")
@click.option("--model-version", default="latest", show_default=True, help="model version to fetch")
@click.option(
    "--output-file",
    "-o",
    required=True,
    type=click.Path(dir_okay=False, path_type=Path),
    help="path to write the fetched model object to",
)
@click.option("--kwargs", default=None, help="JSON keyword arguments forwarded to the model saver")
def fetch_model(
    app_ref: str,
    app_version: Optional[str],
    model_version: str,
    output_file: Path,
    kwargs: Optional[str],
) -> None:
    """Fetch a trained model from the backend registry and save it locally
    (reference cli.py:147-164)."""
    model = _locate_model(app_ref)
    saver_kwargs = _parse_json_option(kwargs, "--kwargs") or {}
    model.artifact = model._backend.fetch_latest_artifact(
        model, app_version=app_version, model_version=model_version
    )
    model.save(output_file, **saver_kwargs)
    click.echo(f"Model saved to {output_file}")


@app.command("serve")
@click.argument("app_ref", metavar="APP")
@click.option("--model-path", default=None, type=click.Path(path_type=Path), help="path to the saved model object")
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8000, show_default=True, type=int)
@click.option("--remote", is_flag=True, default=False, help="load the model from the remote backend registry")
@click.option("--app-version", default=None, help="app version for --remote model loading")
@click.option("--model-version", default="latest", show_default=True, help="model version for --remote loading")
@click.option("--workers", default=1, show_default=True, type=int, help="server processes sharing the port (SO_REUSEPORT)")
@click.option(
    "--num-hosts", default=None, type=int,
    help="multi-host fleet serving (docs/serving.md 'Multi-host fleets'): total "
    "processes in the fleet. Host 0 serves the public HTTP front door and "
    "coordinates; hosts > 0 run their engines behind a loopback control server. "
    "Exported as UNIONML_TPU_NUM_PROCESSES before the app module imports",
)
@click.option(
    "--coordinator", default=None, metavar="HOST:PORT",
    help="jax.distributed coordinator address every fleet process rendezvouses "
    "at (required with --num-hosts > 1); exported as UNIONML_TPU_COORDINATOR "
    "before the app module imports — the same bootstrap job_runner uses for "
    "multi-host training",
)
@click.option(
    "--process-id", default=None, type=int,
    help="this process's id in [0, --num-hosts); exported as "
    "UNIONML_TPU_PROCESS_ID before the app module imports",
)
@click.option("--reload", "reload_", is_flag=True, default=False, help="restart the server when app source changes (development)")
@click.option(
    "--log-level",
    default=None,
    type=click.Choice(["debug", "info", "warning", "error"]),
    help="unionml-tpu logger level",
)
@click.option(
    "--max-inflight", default=None, type=int,
    help="concurrent-request admission cap; excess requests shed with 429 + Retry-After (0 = unbounded)",
)
@click.option(
    "--deadline-ms", default=None, type=float,
    help="server-default per-request deadline in ms; expired requests shed with 503 (0 = no default deadline)",
)
@click.option(
    "--max-deadline-ms", default=None, type=float,
    help="ceiling on client-requested X-Request-Deadline-Ms values",
)
@click.option(
    "--drain-timeout", default=None, type=float,
    help="seconds a SIGTERM-initiated graceful drain waits for in-flight requests/streams",
)
@click.option(
    "--dp-replicas", default=None, type=int,
    help="data-parallel replica engines for generation serving: each replica owns a TP submesh "
    "(or its own device) and requests route least-loaded-first (0 = derive from the mesh's "
    "data/fsdp axes)",
)
@click.option(
    "--replica-roles", default=None,
    help="disaggregated serving: per-role replica counts, e.g. 'prefill=1,decode=3' — "
    "prompts above --prefill-threshold prefill on a prefill-role replica and their KV "
    "blocks hand off to a decode replica at admission-complete (token-identical to a "
    "mixed replica, but resident decode streams never stall behind the prefill); "
    "implies the fleet size when --dp-replicas is unset",
)
@click.option(
    "--prefill-threshold", default=None, type=int,
    help="prompt length (tokens) at which an admission takes the prefill→decode "
    "handoff path (0 = every admission, once --replica-roles is set)",
)
@click.option(
    "--autoscale-high", default=None, type=float,
    help="elastic resize: per-replica load watermark above which the fleet adds a "
    "replica on a spare submesh at runtime (also triggered while any replica's SLO "
    "state is breach); 0/unset = autoscaler off",
)
@click.option(
    "--autoscale-low", default=None, type=float,
    help="per-replica load watermark below which the fleet drains one replica "
    "(zero in-flight streams lost); 0 = never scale down",
)
@click.option(
    "--autoscale-interval", default=None, type=float,
    help="seconds between autoscaler evaluations of the fleet's windowed rates",
)
@click.option(
    "--min-replicas", default=None, type=int,
    help="fleet-size floor the autoscaler may never drain below",
)
@click.option(
    "--max-replicas", default=None, type=int,
    help="fleet-size ceiling for the autoscaler (0 = bounded by spare submeshes/devices)",
)
@click.option(
    "--admit-chunk", default=None, type=int,
    help="stall-free admission: slice each generation admission's prefill into this many "
    "tokens per chunk, interleaved with decode dispatches so long prompts never freeze "
    "resident streams (0 = monolithic admission unless the model config sets prefill_chunk)",
)
@click.option(
    "--prefill-budget", default=None, type=int,
    help="prefill tokens the continuous engine may run per iteration between decode "
    "dispatches (0 = one admission chunk)",
)
@click.option(
    "--max-admissions", default=None, type=int,
    help="concurrent partially-prefilled admissions in the continuous engine (0 = 1)",
)
@click.option(
    "--prefix-cache/--no-prefix-cache", "prefix_cache", default=None,
    help="radix prefix cache on paged continuous engines: prompts extending a "
    "previously-seen prefix (system prompt, multi-turn history) reuse its cached KV "
    "blocks and prefill only the suffix; off (the default) keeps today's behavior exactly",
)
@click.option(
    "--compile-cache", "compile_cache", default=None, metavar="DIR",
    help="persistent XLA compilation cache directory (exported as "
    "UNIONML_TPU_COMPILE_CACHE before the app module imports; '1' = the default "
    "location, '0' = off): re-runs of the same program load from disk instead of "
    "recompiling",
)
@click.option(
    "--aot-preload", "aot_preload", is_flag=False, flag_value="1", default=None,
    metavar="[DIR]",
    help="AOT program store for generation serving (bare flag = the default "
    "~/.cache/unionml_tpu/aot): warmup loads serialized executables instead of "
    "compiling — cold-start-to-first-token becomes load-bound — and every compile "
    "actually paid is serialized back for the next cold process; same early-export "
    "contract as --dp-replicas (UNIONML_TPU_AOT_PRELOAD)",
)
@click.option(
    "--quantize", default=None, type=click.Choice(["int8", "none"]),
    help="weight-only quantization for the app's serving Generators: int8 stores matmul "
    "kernels as int8 with per-channel scales (dequant fuses in-jit, so int8 is what "
    "crosses HBM — roughly 2x decode bandwidth); none forces full precision over an "
    "inherited UNIONML_TPU_QUANTIZE export",
)
@click.option(
    "--kv-cache-dtype", "kv_cache_dtype", default=None, type=click.Choice(["int8", "none"]),
    help="KV-cache storage dtype for generation serving: int8 stores K/V rows (dense "
    "rows and paged pools alike) symmetric-quantized per (position, head) with f32 "
    "scales — roughly doubling resident streams per chip; none forces the compute dtype",
)
@click.option(
    "--trace/--no-trace", "trace", default=None,
    help="record a per-request timeline (queue wait, routed replica, prefill chunks, "
    "emissions) into the flight recorder, served at /debug/requests; request ids flow "
    "and echo on every response regardless",
)
@click.option(
    "--flight-recorder-size", default=None, type=int,
    help="completed request timelines the flight recorder retains (ring buffer)",
)
@click.option(
    "--log-format", default=None, type=click.Choice(["text", "json"]),
    help="log line format; json emits structured lines carrying the request id and "
    "turns on the per-request access log",
)
@click.option(
    "--profile-dir", default=None, type=click.Path(file_okay=False, path_type=Path),
    help="directory for on-demand POST /debug/profile jax.profiler captures "
    "(unset disables the endpoint)",
)
@click.option(
    "--record-traffic", "record_traffic", default=None,
    type=click.Path(file_okay=False, path_type=Path),
    help="capture live /v1 and /predict-stream traffic into replayable ndjson "
    "traces in this directory (docs/workloads.md); replay them with "
    "`unionml-tpu replay`",
)
@click.option(
    "--record-traffic-hash", "record_traffic_hash", is_flag=True, default=False,
    help="record prompt SHA-256 digests + lengths instead of token ids "
    "(privacy posture for traces that leave the machine); the replayer "
    "regenerates deterministic same-length prompts",
)
@click.option(
    "--slo-ttft-p95-ms", default=None, type=float,
    help="SLO: time-to-first-token p95 target in ms, evaluated with multi-window "
    "burn rates (ok/warn/breach on /healthz); breaching requests pin their "
    "timelines as exemplars and the replica scheduler routes around a breaching "
    "replica (0 = disarmed)",
)
@click.option(
    "--slo-tbt-p99-ms", default=None, type=float,
    help="SLO: time-between-tokens p99 target in ms (0 = disarmed)",
)
@click.option(
    "--slo-shed-ratio", default=None, type=float,
    help="SLO: tolerated fraction of arrivals shed with 429/503 over the burn-rate "
    "windows, e.g. 0.01 (0 = disarmed)",
)
@click.option(
    "--tenant-config", default=None, type=click.Path(path_type=Path),
    help="multi-tenant QoS: tenants.json with per-tenant fair-share weights, "
    "req/s + generated-tokens/s bucket rates, default priority tiers, and "
    "api-key -> tenant mappings; identified tenants are admitted "
    "deficit-round-robin and bucket-limited (429 + Retry-After from the "
    "bucket's refill time)",
)
@click.option(
    "--default-tenant-rate", default=None, type=float,
    help="req/s bucket rate for identified tenants not named in --tenant-config "
    "(anonymous traffic is never bucket-limited); 0 = unlimited",
)
@click.option(
    "--fault-plan", "fault_plan", default=None, metavar="PLAN",
    help="deterministic fault injection (docs/serving.md 'Fault tolerance'): "
    "a FaultPlan JSON file (or the JSON inline) of seeded worker_kill/"
    "rpc_drop/rpc_delay/stream_cut events keyed on virtual time and host id; "
    "exported as UNIONML_TPU_FAULT_PLAN before the app module imports",
)
@click.option(
    "--probe-interval", default=None, type=float,
    help="seconds between fleet reconciliation ticks (lease heartbeat, "
    "suspect/dead re-probes, rendezvous announce scans)",
)
@click.option(
    "--probation-probes", default=None, type=int,
    help="consecutive successful probes a returning host must pass in "
    "probation before it takes traffic again",
)
@click.option(
    "--lease-ttl", default=None, type=float,
    help="coordinator heartbeat-lease TTL in seconds; workers promote the "
    "lowest-id live worker when the lease expires",
)
def serve(
    app_ref: str,
    model_path: Optional[Path],
    host: str,
    port: int,
    remote: bool,
    app_version: Optional[str],
    model_version: str,
    workers: int,
    num_hosts: Optional[int],
    coordinator: Optional[str],
    process_id: Optional[int],
    reload_: bool,
    log_level: Optional[str],
    max_inflight: Optional[int],
    deadline_ms: Optional[float],
    max_deadline_ms: Optional[float],
    drain_timeout: Optional[float],
    dp_replicas: Optional[int],
    replica_roles: Optional[str],
    prefill_threshold: Optional[int],
    autoscale_high: Optional[float],
    autoscale_low: Optional[float],
    autoscale_interval: Optional[float],
    min_replicas: Optional[int],
    max_replicas: Optional[int],
    admit_chunk: Optional[int],
    prefill_budget: Optional[int],
    max_admissions: Optional[int],
    prefix_cache: Optional[bool],
    compile_cache: Optional[str],
    aot_preload: Optional[str],
    quantize: Optional[str],
    kv_cache_dtype: Optional[str],
    trace: Optional[bool],
    flight_recorder_size: Optional[int],
    log_format: Optional[str],
    profile_dir: Optional[Path],
    record_traffic: Optional[Path],
    record_traffic_hash: bool,
    slo_ttft_p95_ms: Optional[float],
    slo_tbt_p99_ms: Optional[float],
    slo_shed_ratio: Optional[float],
    tenant_config: Optional[Path],
    default_tenant_rate: Optional[float],
    fault_plan: Optional[str],
    probe_interval: Optional[float],
    probation_probes: Optional[int],
    lease_ttl: Optional[float],
) -> None:
    """Start the HTTP prediction service (reference cli.py:172-205).

    The reference clones uvicorn's CLI (workers/reload/log config included) and
    injects ``--model-path`` via the ``UNIONML_MODEL_PATH`` env var, refusing to
    run when the variable is pre-set (cli.py:187-202); identical semantics here,
    on our own server. ``--workers N`` forks N processes sharing the port via
    SO_REUSEPORT — right for host-side (sklearn) predictors; a TPU predictor
    should stay at 1 worker and scale through micro-batching, since the chip is
    a single shared resource. ``--reload`` watches the app module's directory
    and restarts on change.

    Overload knobs (docs/serving.md "Serving under load"): ``--max-inflight``
    caps concurrently executing requests (excess shed 429 + Retry-After),
    ``--deadline-ms``/``--max-deadline-ms`` bound per-request deadlines
    (expired work shed 503), and ``--drain-timeout`` bounds the SIGTERM
    graceful drain (readiness flips, in-flight streams finish, then exit).

    ``--dp-replicas N`` (docs/serving.md "Data-parallel serving") replicates
    the app's continuous generation engine N ways — one TP submesh (or device)
    per replica, least-loaded routing, per-replica occupancy on ``/metrics``.
    Exported as an env var BEFORE the app module imports, so engines built at
    import time replicate too.

    ``--replica-roles`` (docs/serving.md "Disaggregated and elastic serving")
    splits the fleet DistServe-style: prompts at least ``--prefill-threshold``
    tokens long prefill on a prefill-role replica and their finished KV
    blocks hand off to a decode replica — token-identical to a mixed replica,
    with resident decode streams never stalling behind a long prefill.
    ``--autoscale-high``/``--autoscale-low`` arm the elastic resize loop:
    above the high watermark (or while any replica's SLO state is breach) a
    replica is added on a spare submesh at runtime, below the low watermark
    one drains with zero in-flight streams lost, bounded by
    ``--min-replicas``/``--max-replicas`` and evaluated every
    ``--autoscale-interval`` seconds. All exported before the app module
    imports, like ``--dp-replicas``.

    ``--admit-chunk`` / ``--prefill-budget`` / ``--max-admissions``
    (docs/serving.md "Stall-free admission") chunk the continuous engine's
    admission prefill and interleave it with decode, bounding resident
    streams' time-between-tokens at ~one chunk while a long prompt admits;
    same early-export contract as ``--dp-replicas``.

    ``--prefix-cache`` (docs/serving.md "Prefix caching") enables the radix
    prefix cache on paged continuous engines: any prompt extending a
    previously-seen prefix skips prefill for the cached portion, bit-identical
    to a cold prefill; same early-export contract as ``--dp-replicas``.

    ``--quantize int8`` / ``--kv-cache-dtype int8`` (docs/serving.md
    "Quantized serving") store serving weights and the KV cache as int8 —
    decode is HBM-bandwidth bound, so both roughly halve bytes per step, and
    int8 paged pools roughly double resident streams per chip. Exported as
    ``UNIONML_TPU_QUANTIZE``/``UNIONML_TPU_KV_CACHE_DTYPE`` before the app
    module imports; Generators built by app code resolve them at construction,
    so existing apps quantize with zero code changes. ``none`` forces full
    precision over an inherited export. Composes with ``--prefix-cache``
    (cached int8 blocks replay bit-identically) and ``--dp-replicas`` (each
    replica quantizes its own placement).

    Cold start (docs/serving.md "Cold start and AOT preload"):
    ``--compile-cache DIR`` points JAX's persistent compilation cache at a
    directory so identical programs skip XLA recompilation across processes,
    and ``--aot-preload [DIR]`` arms the AOT program store — serving warmup
    then *loads* serialized generator executables (prefill per bucket,
    decode, admission scatter/gather) instead of compiling them, making
    cold-start-to-first-token load-bound; compiles actually paid are
    serialized back for the next cold process, ``scale_to`` scale-ups onto a
    previously-used submesh join without a fresh XLA trace, and the
    serverless handler restores its programs on the first invocation. Both
    exported before the app module imports, like ``--dp-replicas``.

    Observability (docs/observability.md): ``--trace`` records per-request
    timelines into the flight recorder (``GET /debug/requests``,
    ``GET /debug/requests/<id>``), ``--flight-recorder-size`` bounds the ring,
    ``--log-format json`` emits structured log lines carrying the request id,
    and ``--profile-dir`` enables on-demand ``POST /debug/profile`` captures.
    All exported as env vars before the app module imports, so engines and
    loggers built at import time see them.

    SLOs and fleet health (docs/observability.md "SLOs and fleet health"):
    ``--slo-ttft-p95-ms`` / ``--slo-tbt-p99-ms`` / ``--slo-shed-ratio``
    declare targets every continuous engine evaluates with multi-window burn
    rates (fast window pages, slow window confirms the trend) through an
    ok→warn→breach state machine. ``GET /healthz`` reports the fleet health
    score with per-replica windowed rates and SLO states, ``GET /debug/fleet``
    adds the routing view, requests that individually blow a target are pinned
    as exemplars at ``/debug/requests?slo=breach``, and the replica scheduler
    routes new work around a breaching replica. Same early-export contract as
    the other knobs (``UNIONML_TPU_SLO_*``).

    Multi-host fleets (docs/serving.md "Multi-host fleets"):
    ``--num-hosts N --coordinator HOST:PORT --process-id I`` runs this serve
    process as one member of an N-host fleet. Every process joins one
    jax.distributed runtime (the same bootstrap ``job_runner`` uses for
    multi-host training), process 0 serves the public HTTP front door with a
    FleetCoordinator routing over every host's engines — fleet-global
    prefix-cache routing, cross-host prefill→decode handoff of block-native
    KV pages, per-host sections on ``/metrics``/``/healthz``/``/debug/fleet``
    — and processes > 0 run their engines behind a loopback control server.
    Same early-export contract as ``--dp-replicas``
    (``UNIONML_TPU_COORDINATOR``/``NUM_PROCESSES``/``PROCESS_ID``).

    Fault tolerance (docs/serving.md "Fault tolerance"): ``--probe-interval``
    / ``--probation-probes`` / ``--lease-ttl`` tune the fleet coordinator's
    host lifecycle (a transport failure suspects a host, probation probes +
    warmup readmit it) and the coordinator heartbeat lease workers watch for
    failover; ``--fault-plan`` arms a deterministic chaos schedule
    (serving/faults.py) for drills and the ``fleet_chaos`` bench lane. Same
    early-export contract as ``--dp-replicas``.

    Multi-tenant QoS (docs/serving.md "Multi-tenant QoS"):
    ``--tenant-config tenants.json`` / ``--default-tenant-rate R`` arm the
    tenancy subsystem — tenant identity from ``X-Tenant-Id`` or the
    ``Authorization`` bearer key, per-tenant token buckets shedding 429 with
    a refill-derived ``Retry-After``, weighted-fair (deficit-round-robin)
    admission in the continuous engine, and ``X-Priority: high`` admissions
    that may preempt a lowest-priority resident (which resumes
    token-identically). The OpenAI-compatible ``POST /v1/completions`` /
    ``/v1/chat/completions`` routes are always served; the tenancy knobs
    make them multi-tenant. Same early-export contract as ``--dp-replicas``.
    """
    if num_hosts is not None or coordinator is not None or process_id is not None:
        # multi-host fleet bootstrap knobs: validate NOW (a typo'd explicit
        # flag is a usage error), then export before the app module imports so
        # engines built at import time see the multi-process runtime — the
        # --dp-replicas contract, shared with job_runner's training bootstrap
        from unionml_tpu import defaults as _defaults

        resolved_hosts = num_hosts if num_hosts is not None else 1
        if resolved_hosts < 1:
            raise click.ClickException("--num-hosts must be >= 1")
        if resolved_hosts > 1 and coordinator is None:
            raise click.ClickException(
                "--num-hosts > 1 needs --coordinator HOST:PORT (the jax.distributed rendezvous)"
            )
        if process_id is not None and not (0 <= process_id < resolved_hosts):
            raise click.ClickException(
                f"--process-id must be in [0, {resolved_hosts}); got {process_id}"
            )
        if num_hosts is not None:
            os.environ[_defaults.DISTRIBUTED_NUM_PROCESSES_ENV_VAR] = str(num_hosts)
        if coordinator is not None:
            os.environ[_defaults.DISTRIBUTED_COORDINATOR_ENV_VAR] = coordinator
        if process_id is not None:
            os.environ[_defaults.DISTRIBUTED_PROCESS_ID_ENV_VAR] = str(process_id)
    if dp_replicas is not None:
        if dp_replicas < 0:
            raise click.ClickException("--dp-replicas must be >= 0 (0 = derive from the mesh)")
        # before _locate_model: app modules often build their engines at import
        from unionml_tpu.defaults import SERVE_DP_REPLICAS_ENV_VAR

        os.environ[SERVE_DP_REPLICAS_ENV_VAR] = str(dp_replicas)
    if replica_roles is not None:
        # validate NOW (a typo'd explicit flag is a usage error, unlike an
        # inherited env, which the ReplicaSet degrades on with a warning),
        # then export before the app module imports — the --dp-replicas
        # contract
        from unionml_tpu import defaults as _defaults

        try:
            _defaults.parse_replica_roles(replica_roles)
        except ValueError as exc:
            raise click.ClickException(f"--replica-roles: {exc}")
        os.environ[_defaults.SERVE_REPLICA_ROLES_ENV_VAR] = replica_roles
    disagg_knobs = (
        ("--prefill-threshold", prefill_threshold, "SERVE_PREFILL_THRESHOLD_ENV_VAR", int),
        ("--autoscale-high", autoscale_high, "SERVE_AUTOSCALE_HIGH_ENV_VAR", float),
        ("--autoscale-low", autoscale_low, "SERVE_AUTOSCALE_LOW_ENV_VAR", float),
        ("--autoscale-interval", autoscale_interval, "SERVE_AUTOSCALE_INTERVAL_S_ENV_VAR", float),
        ("--min-replicas", min_replicas, "SERVE_MIN_REPLICAS_ENV_VAR", int),
        ("--max-replicas", max_replicas, "SERVE_MAX_REPLICAS_ENV_VAR", int),
    )
    if any(value is not None for _, value, _, _ in disagg_knobs):
        from unionml_tpu import defaults as _defaults

        for flag, value, env_name, cast in disagg_knobs:
            if value is None:
                continue
            floor = 1 if flag == "--min-replicas" else 0
            if value < floor:
                raise click.ClickException(f"{flag} must be >= {floor}")
            os.environ[getattr(_defaults, env_name)] = repr(cast(value))
    if prefix_cache is not None:
        # same early-export contract as --dp-replicas: paged engines built at
        # app-module import time must see the knob
        from unionml_tpu.defaults import SERVE_PREFIX_CACHE_ENV_VAR

        os.environ[SERVE_PREFIX_CACHE_ENV_VAR] = "1" if prefix_cache else "0"
    if compile_cache is not None or aot_preload is not None:
        # same early-export contract as --dp-replicas: engines (and the
        # package-import compile-cache hook in reload/fork children) must see
        # the knobs before the app module imports. --compile-cache also takes
        # effect NOW — this process's import hook already ran with the old env
        from unionml_tpu import defaults as _defaults

        if compile_cache is not None:
            os.environ[_defaults.SERVE_COMPILE_CACHE_ENV_VAR] = compile_cache
            if compile_cache.strip().lower() not in ("", "0", "false", "no", "off"):
                from unionml_tpu.compile_cache import enable_compile_cache

                try:
                    enable_compile_cache(compile_cache)
                except Exception as exc:
                    raise click.ClickException(f"--compile-cache {compile_cache}: {exc}")
        if aot_preload is not None:
            os.environ[_defaults.SERVE_AOT_PRELOAD_ENV_VAR] = aot_preload
    if quantize is not None or kv_cache_dtype is not None:
        # same early-export contract: Generators built at app-module import
        # time resolve these at construction ("none" exports too — it must
        # override an inherited fleet-wide env in reload/fork children)
        from unionml_tpu import defaults as _defaults

        if quantize is not None:
            os.environ[_defaults.SERVE_QUANTIZE_ENV_VAR] = quantize
        if kv_cache_dtype is not None:
            os.environ[_defaults.SERVE_KV_CACHE_DTYPE_ENV_VAR] = kv_cache_dtype
    admission_knobs = (
        ("--admit-chunk", admit_chunk, "SERVE_ADMIT_CHUNK_ENV_VAR"),
        ("--prefill-budget", prefill_budget, "SERVE_PREFILL_BUDGET_ENV_VAR"),
        ("--max-admissions", max_admissions, "SERVE_MAX_ADMISSIONS_ENV_VAR"),
    )
    if any(value is not None for _, value, _ in admission_knobs):
        from unionml_tpu import defaults as _defaults

        for flag, value, env_name in admission_knobs:
            if value is None:
                continue
            if value < 0:
                raise click.ClickException(f"{flag} must be >= 0 (0 = default)")
            # same early-export contract as --dp-replicas: engines built at
            # app-module import time must see the knobs
            os.environ[getattr(_defaults, env_name)] = str(value)
    slo_knobs = (
        ("--slo-ttft-p95-ms", slo_ttft_p95_ms, "SERVE_SLO_TTFT_P95_MS_ENV_VAR"),
        ("--slo-tbt-p99-ms", slo_tbt_p99_ms, "SERVE_SLO_TBT_P99_MS_ENV_VAR"),
        ("--slo-shed-ratio", slo_shed_ratio, "SERVE_SLO_SHED_RATIO_ENV_VAR"),
    )
    if any(value is not None for _, value, _ in slo_knobs):
        from unionml_tpu import defaults as _defaults

        for flag, value, env_name in slo_knobs:
            if value is None:
                continue
            if value < 0:
                raise click.ClickException(f"{flag} must be >= 0 (0 = disarmed)")
            # same early-export contract as --dp-replicas: every continuous
            # engine's SLO tracker reads the env at construction, so engines
            # built at app-module import time get the targets too
            os.environ[getattr(_defaults, env_name)] = repr(value)
    if tenant_config is not None or default_tenant_rate is not None:
        # same early-export contract as --dp-replicas: the serving app builds
        # its TenantRegistry from the env at construction, and reload/fork
        # children inherit the knobs
        from unionml_tpu import defaults as _defaults

        if tenant_config is not None:
            if not tenant_config.exists():
                raise click.ClickException(f"--tenant-config {tenant_config} does not exist")
            os.environ[_defaults.SERVE_TENANT_CONFIG_ENV_VAR] = str(tenant_config)
        if default_tenant_rate is not None:
            if default_tenant_rate < 0:
                raise click.ClickException("--default-tenant-rate must be >= 0 (0 = unlimited)")
            os.environ[_defaults.SERVE_DEFAULT_TENANT_RATE_ENV_VAR] = repr(default_tenant_rate)
    if (
        fault_plan is not None or probe_interval is not None
        or probation_probes is not None or lease_ttl is not None
    ):
        # fleet fault-tolerance knobs (docs/serving.md "Fault tolerance"):
        # validate NOW (a typo'd explicit flag is a usage error), then export
        # before the app module imports — the --dp-replicas contract
        from unionml_tpu import defaults as _defaults
        from unionml_tpu.serving.faults import FaultPlan as _FaultPlan

        if fault_plan is not None:
            try:
                if fault_plan.lstrip().startswith("{"):
                    _FaultPlan.parse(fault_plan)
                else:
                    _FaultPlan.load(fault_plan)
            except (OSError, ValueError) as exc:
                raise click.ClickException(f"--fault-plan: {exc}")
            os.environ[_defaults.SERVE_FAULT_PLAN_ENV_VAR] = fault_plan
        if probe_interval is not None:
            if probe_interval <= 0:
                raise click.ClickException("--probe-interval must be > 0 seconds")
            os.environ[_defaults.FLEET_PROBE_INTERVAL_S_ENV_VAR] = repr(probe_interval)
        if probation_probes is not None:
            if probation_probes < 1:
                raise click.ClickException("--probation-probes must be >= 1")
            os.environ[_defaults.FLEET_PROBATION_PROBES_ENV_VAR] = str(probation_probes)
        if lease_ttl is not None:
            if lease_ttl <= 0:
                raise click.ClickException("--lease-ttl must be > 0 seconds")
            os.environ[_defaults.FLEET_LEASE_TTL_S_ENV_VAR] = repr(lease_ttl)
    # observability knobs: same early-export contract as --dp-replicas (the
    # serving app reads them at construction; reload/fork children inherit)
    if trace is not None or flight_recorder_size is not None or profile_dir is not None:
        from unionml_tpu import defaults as _defaults

        if trace is not None:
            os.environ[_defaults.SERVE_TRACE_ENV_VAR] = "1" if trace else "0"
        if flight_recorder_size is not None:
            if flight_recorder_size < 1:
                raise click.ClickException("--flight-recorder-size must be >= 1")
            os.environ[_defaults.SERVE_FLIGHT_RECORDER_ENV_VAR] = str(flight_recorder_size)
        if profile_dir is not None:
            os.environ[_defaults.SERVE_PROFILE_DIR_ENV_VAR] = str(profile_dir)
    if record_traffic is not None:
        # same early-export contract: the ServingApp builds its TraceRecorder
        # from the env at construction (docs/workloads.md)
        from unionml_tpu import defaults as _defaults

        os.environ[_defaults.SERVE_RECORD_TRAFFIC_ENV_VAR] = str(record_traffic)
        if record_traffic_hash:
            os.environ[_defaults.SERVE_RECORD_TRAFFIC_HASH_ENV_VAR] = "1"
    if log_format is not None:
        from unionml_tpu import defaults as _defaults
        from unionml_tpu._logging import set_log_format

        set_log_format(log_format)
        os.environ[_defaults.SERVE_LOG_FORMAT_ENV_VAR] = log_format
    if log_level is not None:
        from unionml_tpu._logging import logger as package_logger

        package_logger.setLevel(log_level.upper())
        os.environ["UNIONML_TPU_LOGLEVEL"] = log_level.upper()  # reload/fork children inherit it
    if model_path is not None:
        if os.getenv(MODEL_PATH_ENV_VAR) is not None:
            raise click.ClickException(
                f"{MODEL_PATH_ENV_VAR} environment variable is already set, which takes precedence "
                "over the --model-path option. Unset it to use --model-path."
            )
        if not model_path.exists():
            raise click.ClickException(f"model path {model_path} does not exist")
        os.environ[MODEL_PATH_ENV_VAR] = str(model_path)

    if reload_:
        _serve_with_reload(app_ref)
        return

    target = _locate_model(app_ref)
    from unionml_tpu.serving import ServingApp

    if isinstance(target, ServingApp):
        serving = target
    else:
        serving = target.serve(remote=remote, app_version=app_version, model_version=model_version)
    serving.configure_overload(
        max_inflight=max_inflight,
        default_deadline_ms=deadline_ms,
        max_deadline_ms=max_deadline_ms,
        drain_timeout_s=drain_timeout,
    ).configure_replicas(
        dp_replicas, replica_roles=replica_roles, prefill_threshold=prefill_threshold
    ).configure_quantization(
        quantize=quantize, kv_cache_dtype=kv_cache_dtype
    ).configure_cold_start(
        compile_cache=compile_cache, aot_preload=aot_preload
    ).configure_observability(
        trace=trace,
        flight_recorder_size=flight_recorder_size,
        log_format=log_format,
        profile_dir=str(profile_dir) if profile_dir is not None else None,
    ).configure_tenancy(
        tenant_config=str(tenant_config) if tenant_config is not None else None,
        default_tenant_rate=default_tenant_rate,
    )

    from unionml_tpu.defaults import distributed_num_processes

    if distributed_num_processes() > 1:
        # multi-host fleet: host 0 serves the public front door over a
        # FleetCoordinator; hosts > 0 run only the control server. --workers
        # forking doesn't compose with a per-process jax runtime.
        if workers > 1:
            raise click.ClickException("--workers does not compose with --num-hosts; scale via hosts")
        from unionml_tpu.serving.cluster import enable_serve_cluster

        enable_serve_cluster(serving, host=host, port=port)
        return

    if workers > 1:
        import signal

        # load the artifact once, then fork: children inherit it copy-on-write and
        # the kernel balances accepted connections across the shared port
        serving.startup()
        children: "list[int]" = []
        for _ in range(workers - 1):
            pid = os.fork()
            if pid == 0:
                serving.run(host=host, port=port, reuse_port=True)
                os._exit(0)
            children.append(pid)

        def stop_children(signum=None, frame=None):
            # killing the parent must not orphan workers holding the port
            for child_pid in children:
                try:
                    os.kill(child_pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
            for child_pid in children:
                try:
                    os.waitpid(child_pid, 0)
                except ChildProcessError:
                    pass
            if signum is not None:
                raise SystemExit(0)

        signal.signal(signal.SIGTERM, stop_children)
        try:
            serving.run(host=host, port=port, reuse_port=True)
        finally:
            stop_children()
    else:
        serving.run(host=host, port=port)


@app.command("replay")
@click.argument("trace", metavar="TRACE")
@click.option(
    "--target", default=None, metavar="URL",
    help="replay against a live server (base URL, e.g. http://127.0.0.1:8000)",
)
@click.option(
    "--self-host", "self_host", default=None, metavar="APP",
    help="host the app in-process (module:variable of a Model or ServingApp — "
    "the `serve` APP argument) and replay through its HTTP dispatch surface",
)
@click.option(
    "--model-path", default=None, type=click.Path(path_type=Path),
    help="path to the saved model object for --self-host (the serve contract)",
)
@click.option("--seed", default=0, show_default=True, type=int,
              help="scenario seed for a scenario:<name> TRACE")
@click.option("--rate-scale", default=1.0, show_default=True, type=float,
              help="compress (>1) or stretch (<1) the trace's arrival schedule")
@click.option("--concurrency", default=32, show_default=True, type=int,
              help="in-flight request cap (hitting it reads as schedule lag)")
@click.option("--grace-ms", default=250.0, show_default=True, type=float,
              help="launch-lag tolerance counted as schedule-adherent")
@click.option(
    "--out", default=None, type=click.Path(dir_okay=False, path_type=Path),
    help="write the report JSON here as well as stdout",
)
@click.option(
    "--fault-plan", "fault_plan", default=None, metavar="PLAN",
    help="chaos mode (--self-host only): arm this FaultPlan (JSON file or "
    "inline) on the app's fleet coordinator when the replay starts, and add "
    "the availability section (success/clean-error ratios, per-fault "
    "recovery-to-first-routed-token) to the report",
)
def replay_cmd(
    trace: str,
    target: Optional[str],
    self_host: Optional[str],
    model_path: Optional[Path],
    seed: int,
    rate_scale: float,
    concurrency: int,
    grace_ms: float,
    out: Optional[Path],
    fault_plan: Optional[str],
) -> None:
    """Replay a traffic trace through the real HTTP stack and judge it.

    TRACE is a trace file (``serve --record-traffic`` output, or
    ``write_trace``), or ``scenario:<name>`` for a library mix
    (``scenario:chat_multiturn``, ``scenario:rag_long_prompt``,
    ``scenario:burst_tenants``, ``scenario:deadline_heavy``) synthesized
    deterministically from ``--seed``. Exactly one of ``--target`` (live
    server over sockets) or ``--self-host`` (in-process ServingApp, the
    serving-test dispatch surface) selects the system under test.

    The report (stdout, and ``--out``) carries per-request-derived per-tenant
    TTFT/TBT/e2e/shed aggregates, wall-clock schedule adherence, and — for
    scenario traces, whose library declares per-tenant SLO targets — a
    verdict block (pass/warn/breach with burn rates). Exit code 1 when any
    judged tenant breaches: a replay run is a judgment, not just numbers
    (docs/workloads.md)."""
    from unionml_tpu.workloads import (
        read_trace,
        replay,
        scenario_meta,
        scenario_targets,
        synthesize,
    )

    if (target is None) == (self_host is None):
        raise click.ClickException("pass exactly one of --target URL or --self-host APP")
    if trace.startswith("scenario:"):
        name = trace.split(":", 1)[1]
        try:
            requests = synthesize(name, seed)
            targets = scenario_targets(name)
            meta = scenario_meta(name, seed)
        except ValueError as exc:
            raise click.ClickException(str(exc))
    else:
        try:
            meta, requests = read_trace(trace)
        except (OSError, ValueError) as exc:
            raise click.ClickException(f"could not read trace {trace!r}: {exc}")
        # a synthesized trace file remembers its scenario: reuse its targets
        targets = None
        if meta.get("scenario"):
            try:
                targets = scenario_targets(str(meta["scenario"]))
            except ValueError:
                targets = None
    plan = None
    if fault_plan is not None:
        if self_host is None:
            raise click.ClickException(
                "--fault-plan needs --self-host (the plan arms the app's own fleet "
                "coordinator; a --target server arms its own via serve --fault-plan)"
            )
        from unionml_tpu.serving.faults import FaultPlan

        try:
            if fault_plan.lstrip().startswith("{"):
                plan = FaultPlan.parse(fault_plan)
            else:
                plan = FaultPlan.load(fault_plan)
        except (OSError, ValueError) as exc:
            raise click.ClickException(f"--fault-plan: {exc}")
    serving = None
    if self_host is not None:
        if model_path is not None:
            if os.getenv(MODEL_PATH_ENV_VAR) is not None:
                raise click.ClickException(
                    f"{MODEL_PATH_ENV_VAR} is already set and takes precedence over "
                    "--model-path; unset it first"
                )
            if not model_path.exists():
                raise click.ClickException(f"model path {model_path} does not exist")
            os.environ[MODEL_PATH_ENV_VAR] = str(model_path)
        located = _locate_model(self_host)
        from unionml_tpu.serving import ServingApp

        serving = located if isinstance(located, ServingApp) else located.serve()
        serving.startup()
    fault_times = None
    if plan is not None:
        engine = getattr(serving.model, "generation_batcher", None)
        arm = getattr(engine, "arm_faults", None)
        if not callable(arm):
            raise click.ClickException(
                "--fault-plan needs a fleet coordinator behind the app "
                "(serve --num-hosts; a single-engine app has no host lifecycle to chaos)"
            )
        arm(plan)  # virtual time starts now — the replay launches immediately
        fault_times = plan.fault_times()
    report = replay(
        requests,
        app=serving,
        target=target,
        concurrency=concurrency,
        rate_scale=rate_scale,
        grace_s=grace_ms / 1000.0,
        targets=targets,
        meta=meta,
        fault_times_s=fault_times,
    )
    rendered = json.dumps(report, indent=2)
    click.echo(rendered)
    if out is not None:
        out.write_text(rendered)
    if report.get("verdict_state") == "breach":
        raise SystemExit(1)


def _app_source_files(app_ref: str) -> "dict[Path, float]":
    """Snapshot mtimes of every .py under the app module's directory."""
    module_name = app_ref.split(":", 1)[0]
    import importlib.util

    spec = importlib.util.find_spec(module_name)
    root = Path(spec.origin).parent if spec and spec.origin else Path.cwd()
    return {p: p.stat().st_mtime for p in root.rglob("*.py") if ".git" not in p.parts}


def _serve_with_reload(app_ref: str, poll_interval: float = 0.5) -> None:
    """Run the server as a child process; restart it when app source changes."""
    import signal
    import subprocess
    import time

    # re-exec through the interpreter: argv[0] may be a module path (python -m)
    # that is not itself executable. --model-path is dropped from the child argv:
    # the parent already validated it and exported UNIONML_MODEL_PATH, which
    # the child inherits (passing both would trip the env-var guard).
    argv = [sys.executable]
    skip_next = False
    for arg in sys.argv:
        if skip_next:
            skip_next = False
            continue
        if arg == "--reload":
            continue
        if arg == "--model-path":
            skip_next = True
            continue
        if arg.startswith("--model-path="):
            continue
        argv.append(arg)
    current: "list[Any]" = [None]

    def forward_term(signum, frame):  # terminating the watcher must stop the server
        if current[0] is not None and current[0].poll() is None:
            current[0].terminate()
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, forward_term)

    def stop_child(child) -> None:
        child.send_signal(signal.SIGTERM)
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:  # slow drain / ignored SIGTERM
            child.kill()
            child.wait()

    while True:
        snapshot = _app_source_files(app_ref)
        child = subprocess.Popen(argv, env=os.environ)
        current[0] = child
        try:
            while child.poll() is None:
                time.sleep(poll_interval)
                if _app_source_files(app_ref) != snapshot:
                    click.echo("source change detected; restarting server", err=True)
                    stop_child(child)
                    break
            else:
                if child.returncode == 0:
                    sys.exit(0)  # clean self-exit
                # crashed (e.g. a transient syntax error was saved): keep watching
                # and respawn on the NEXT source change, like uvicorn's reloader
                click.echo(
                    f"server exited with code {child.returncode}; waiting for a source change",
                    err=True,
                )
                while _app_source_files(app_ref) == snapshot:
                    time.sleep(poll_interval)
                click.echo("source change detected; restarting server", err=True)
        except KeyboardInterrupt:  # pragma: no cover
            stop_child(child)
            raise


def main() -> None:  # console-script entry point (reference setup.py:34)
    app()


if __name__ == "__main__":
    main()
