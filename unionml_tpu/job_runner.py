"""Worker entrypoint: execute one job spec from the backend store.

The per-host analog of the reference's container entrypoint + task resolver
(unionml/task_resolver.py:16-21): re-import the deployed app module from the bundle,
rebuild the requested workflow, run it, write outputs. Launched as
``python -m unionml_tpu.job_runner <execution_dir>`` on every host of a slice; when
``UNIONML_TPU_COORDINATOR`` is set the hosts join one JAX distributed runtime before
executing, so pjit-compiled stages span the whole slice.
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import traceback
from pathlib import Path


def _maybe_init_distributed() -> None:
    coordinator = os.environ.get("UNIONML_TPU_COORDINATOR")
    if not coordinator:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(os.environ.get("UNIONML_TPU_NUM_PROCESSES", "1")),
        process_id=int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")),
    )


def run_job(execution_dir: str) -> None:
    exec_path = Path(execution_dir)
    status = exec_path / "status"
    outputs = exec_path / "outputs"
    outputs.mkdir(exist_ok=True)
    status.write_text("RUNNING")
    try:
        with open(exec_path / "spec.pkl", "rb") as f:
            spec = pickle.load(f)

        _maybe_init_distributed()

        from unionml_tpu.resolver import locate

        model = locate(spec["app_module"])
        inputs = spec["inputs"]

        if spec["kind"] == "train":
            model.train(
                hyperparameters=inputs.get("hyperparameters"),
                loader_kwargs=inputs.get("loader_kwargs"),
                splitter_kwargs=inputs.get("splitter_kwargs"),
                parser_kwargs=inputs.get("parser_kwargs"),
                trainer_kwargs=inputs.get("trainer_kwargs"),
                **(inputs.get("reader_kwargs") or {}),
            )
            # only process 0 of a slice persists outputs (single writer)
            if int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")) == 0:
                model.save(outputs / "model_object.bin")
                hp = model.artifact.hyperparameters
                from dataclasses import is_dataclass

                from unionml_tpu.utils import dataclass_to_dict

                meta = {
                    "hyperparameters": dataclass_to_dict(hp) if is_dataclass(hp) else hp,
                    "metrics": model.artifact.metrics,
                }
                (outputs / "artifact.json").write_text(json.dumps(meta, default=str))
        elif spec["kind"] == "predict":
            model_exec_outputs = Path(spec["model_execution"]) / "outputs"
            model.load(model_exec_outputs / "model_object.bin")
            features = inputs.get("features")
            if features is not None:
                predictions = model.predict(features=features)
            else:
                predictions = model.predict(**(inputs.get("reader_kwargs") or {}))
            if int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")) == 0:
                with open(outputs / "predictions.pkl", "wb") as f:
                    pickle.dump(predictions, f)
        else:
            raise ValueError(f"unknown job kind: {spec['kind']}")

        status.write_text("SUCCEEDED")
    except Exception:
        traceback.print_exc()
        status.write_text("FAILED")
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print("usage: python -m unionml_tpu.job_runner <execution_dir>", file=sys.stderr)
        sys.exit(2)
    run_job(sys.argv[1])
