"""Worker entrypoint: execute one job spec from the backend store.

The per-host analog of the reference's container entrypoint + task resolver
(unionml/task_resolver.py:16-21): re-import the deployed app module from the bundle,
rebuild the requested workflow, run it, write outputs. Launched as
``python -m unionml_tpu.job_runner <execution_dir>`` on every host of a slice; when
``UNIONML_TPU_COORDINATOR`` is set the hosts join one JAX distributed runtime before
executing, so pjit-compiled stages span the whole slice.

Failure detection (SURVEY.md §5.3 — absent in the reference, which delegates retries
to Flyte): a daemon thread stamps ``<execution_dir>/heartbeat`` every
``UNIONML_TPU_HEARTBEAT_S`` seconds while the job runs. The backend watchdog
(:meth:`unionml_tpu.remote.Backend.wait`) treats a RUNNING execution with a stale
heartbeat as a lost slice and resubmits it; a trainer configured with
``checkpoint_dir`` resumes from its last orbax step checkpoint. Fault injection for
tests: ``UNIONML_TPU_FAULT_INJECT=N`` hard-kills attempts ``< N`` mid-run
(``UNIONML_TPU_FAULT_INJECT_PROCESS=i`` narrows the kill to worker ``i`` — the
lost-single-host scenario on a multi-worker slice).
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import threading
import time
import traceback
from pathlib import Path

from unionml_tpu._logging import logger
from unionml_tpu.defaults import env_float, env_int


def _start_heartbeat(exec_path: Path, my_attempt: int) -> threading.Event:
    """Stamp ``heartbeat`` periodically so the backend can detect a lost worker.

    Fencing: if the attempt counter moves past ``my_attempt`` the backend has
    declared this worker lost and resubmitted — a stalled-but-alive worker waking
    back up must not race the new attempt for the outputs dir, so it kills itself.
    """
    interval = env_float("UNIONML_TPU_HEARTBEAT_S", 5.0, minimum=0.1)
    stop = threading.Event()
    heartbeat = exec_path / "heartbeat"

    def beat() -> None:
        while not stop.is_set():
            if _current_attempt(exec_path) != my_attempt:
                os._exit(43)  # fenced: a newer attempt owns this execution
            try:
                heartbeat.write_text(repr(time.time()))
            except OSError:  # execution dir vanished (cancelled); nothing to report to
                return
            stop.wait(interval)

    threading.Thread(target=beat, daemon=True, name="unionml-tpu-heartbeat").start()
    return stop


def _current_attempt(exec_path: Path) -> int:
    attempt_file = exec_path / "attempt"
    try:
        return int(attempt_file.read_text().strip())
    except (OSError, ValueError):
        return 0


def _maybe_inject_fault(exec_path: Path) -> None:
    """Simulated slice failure: die without writing a terminal status.

    ``UNIONML_TPU_FAULT_INJECT=N`` kills attempts ``< N``. With
    ``UNIONML_TPU_FAULT_INJECT_PROCESS=i`` set, only worker ``i`` dies — the
    lost-single-host scenario on a multi-worker slice (its peers block in the
    first collective until the watchdog reaps them).
    """
    inject_below = env_int("UNIONML_TPU_FAULT_INJECT", 0)
    if _current_attempt(exec_path) >= inject_below:
        return
    target = os.environ.get("UNIONML_TPU_FAULT_INJECT_PROCESS")
    if target is not None and os.environ.get("UNIONML_TPU_PROCESS_ID", "0") != target:
        return
    os._exit(42)


def _maybe_init_distributed() -> None:
    # one bootstrap shared by train and serve (unionml_tpu/distributed.py);
    # the "joined jax.distributed runtime" log line the watchdog tests assert
    # on is emitted there
    from unionml_tpu.distributed import maybe_initialize

    maybe_initialize()


def run_job(execution_dir: str) -> None:
    exec_path = Path(execution_dir)
    status = exec_path / "status"
    outputs = exec_path / "outputs"
    outputs.mkdir(exist_ok=True)
    status.write_text("RUNNING")
    my_attempt = _current_attempt(exec_path)
    stop_heartbeat = _start_heartbeat(exec_path, my_attempt)
    try:
        with open(exec_path / "spec.pkl", "rb") as f:
            spec = pickle.load(f)

        # the one guaranteed log line per worker: what runs, where, which attempt —
        # launcher log streams (files, `docker logs`, `kubectl logs`) key on it
        logger.info(
            f"job_runner: {spec['kind']} {spec['app_module']} "
            f"(attempt {my_attempt}, process {os.environ.get('UNIONML_TPU_PROCESS_ID', '0')})"
        )

        _maybe_init_distributed()
        _maybe_inject_fault(exec_path)

        from unionml_tpu.resolver import locate

        model = locate(spec["app_module"])
        inputs = spec["inputs"]

        if spec["kind"] == "train":
            model.train(
                hyperparameters=inputs.get("hyperparameters"),
                loader_kwargs=inputs.get("loader_kwargs"),
                splitter_kwargs=inputs.get("splitter_kwargs"),
                parser_kwargs=inputs.get("parser_kwargs"),
                trainer_kwargs=inputs.get("trainer_kwargs"),
                **(inputs.get("reader_kwargs") or {}),
            )
            # only process 0 of a slice persists outputs (single writer)
            if int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")) == 0:
                model.save(outputs / "model_object.bin")
                hp = model.artifact.hyperparameters
                from dataclasses import is_dataclass

                from unionml_tpu.utils import dataclass_to_dict

                meta = {
                    "hyperparameters": dataclass_to_dict(hp) if is_dataclass(hp) else hp,
                    "metrics": model.artifact.metrics,
                }
                (outputs / "artifact.json").write_text(json.dumps(meta, default=str))
        elif spec["kind"] == "predict":
            model_exec_outputs = Path(spec["model_execution"]) / "outputs"
            model.load(model_exec_outputs / "model_object.bin")
            features = inputs.get("features")
            if features is not None:
                predictions = model.predict(features=features)
            else:
                predictions = model.predict(**(inputs.get("reader_kwargs") or {}))
            if int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")) == 0:
                with open(outputs / "predictions.pkl", "wb") as f:
                    pickle.dump(predictions, f)
        else:
            raise ValueError(f"unknown job kind: {spec['kind']}")

        if _current_attempt(exec_path) != my_attempt:
            os._exit(43)  # fenced just before commit: a newer attempt owns the outputs
        # only process 0 commits SUCCEEDED: a fast non-primary worker must not mark
        # the execution done while the primary is still writing outputs
        if int(os.environ.get("UNIONML_TPU_PROCESS_ID", "0")) == 0:
            status.write_text("SUCCEEDED")
    except Exception:
        traceback.print_exc()
        if _current_attempt(exec_path) != my_attempt:
            os._exit(43)  # fenced: don't clobber the replacement attempt's status
        try:
            committed = status.read_text().strip() == "SUCCEEDED"
        except OSError:
            committed = False
        if not committed:
            # don't clobber a SUCCEEDED the primary already committed (a late
            # non-primary failure after the outputs are complete is not a job failure)
            status.write_text("FAILED")
        sys.exit(1)
    finally:
        stop_heartbeat.set()


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print("usage: python -m unionml_tpu.job_runner <execution_dir>", file=sys.stderr)
        sys.exit(2)
    run_job(sys.argv[1])
