"""tpu-lint rule engine: walk files, dispatch rules, report findings.

The repo's only static gate used to be the ``compileall`` syntax check
(tests/unit/test_syntax.py) — which exists because a trivially lintable
f-string bug once broke docs collection. The hazards that actually cost TPU
time are semantic, not syntactic: a host sync inside a jitted function turns
an async dispatch into a device round-trip, a donated buffer read after the
call is a use-after-free, an unlocked cross-thread attribute mutation is a
race that only fires under production load. Each is mechanically visible in
the AST; this engine makes them review-time failures instead of TPU-time
mysteries (the same layering JAX's own lint/pytype gates give the upstream
stack).

Architecture: one parse per file feeds BOTH rule protocols. Per-file rules
are stateless classes with a ``check(tree, path)`` method that sees one tree;
interprocedural rules additionally implement ``check_project(index)`` against
the cross-module :class:`~unionml_tpu.analysis.project.ProjectIndex` (symbol
table, class hierarchy, call graph, per-function lock/jit/contextvar facts),
which the engine builds once per run from a content-hash cache — a warm run
re-summarizes only edited files, keeping the tier-1 gate inside its 5 s
budget. Findings from both protocols funnel through per-line
``# tpu-lint: disable=RULE`` and file-level ``# tpu-lint: disable-file=RULE``
(first five lines of a module) suppressions into a :class:`LintResult`; a
JSON baseline (``--baseline``) can additionally absorb known findings so a
stricter rule lands without a same-PR repo sweep — baselined findings are
reported separately and do not fail the gate. Reporters render text
(``path:line: RULE id: message``), a stable JSON schema
(``{"findings": [...], "counts": ...}``, version 1), or SARIF 2.1.0 for CI
annotation surfaces (suppression records carry which mechanism fired;
baseline runs annotate ``baselineState``). Exit codes: 0 clean (justified
suppressions and baselined findings included), 1 findings, 2 usage/parse
errors.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import subprocess
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "all_rules",
    "apply_baseline",
    "load_baseline",
    "main",
    "render_json",
    "render_sarif",
    "render_text",
    "run_lint",
    "write_baseline",
]

#: ``# tpu-lint: disable=TPU001`` or ``disable=TPU001,TPU003`` or ``disable=all``,
#: anywhere on the offending line (typically a trailing comment)
_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\s]+)")

#: ``# tpu-lint: disable-file=TPU016`` (or a comma list, or ``all``) — whole-file
#: opt-out, honored only within the first :data:`_FILE_SUPPRESS_WINDOW` lines so
#: the opt-out is visible at the top of the module, next to the docstring
_FILE_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable-file=([A-Za-z0-9_,\s]+)")
_FILE_SUPPRESS_WINDOW = 5


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, addressable as ``path:line``."""

    rule: str  #: rule id, e.g. "TPU003"
    path: str  #: file path as given to the walker
    line: int  #: 1-indexed source line
    col: int  #: 0-indexed column
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule:
    """Base class for tpu-lint rules.

    Subclasses set ``id``/``title`` and implement :meth:`check`. Rules are
    stateless across files — the engine instantiates each once per run and
    calls ``check`` per file, so a rule must not carry per-file state between
    calls (everything it needs is derivable from the tree).

    Interprocedural rules additionally override :meth:`check_project` (and may
    leave :meth:`check` returning nothing): the engine builds one
    :class:`~unionml_tpu.analysis.project.ProjectIndex` per run and hands it to
    every selected rule after the per-file pass, so a rule can follow call
    graphs, lock orders, and contextvar flows across module boundaries.
    """

    id: str = ""
    title: str = ""

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        raise NotImplementedError

    def check_project(self, index) -> "List[Finding]":
        """Whole-program pass over the cross-module index; default: nothing.

        Findings may duplicate :meth:`check`'s (e.g. TPU001's project pass
        re-walks intra-module reachability on its way across modules) — the
        engine deduplicates on (rule, path, line, col)."""
        return []

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


def all_rules() -> "List[Rule]":
    """Fresh instances of every registered rule, in id order."""
    from unionml_tpu.analysis.rules import RULES

    return [cls() for _, cls in sorted(RULES.items())]


@dataclasses.dataclass
class LintResult:
    """What a lint run produced: active findings, suppressed findings, errors."""

    findings: "List[Finding]" = dataclasses.field(default_factory=list)
    suppressed: "List[Finding]" = dataclasses.field(default_factory=list)
    #: files that failed to parse (path, message) — reported and exit-coded 2,
    #: since an unparseable file is a gate failure of its own
    errors: "List[Tuple[str, str]]" = dataclasses.field(default_factory=list)
    files: int = 0
    #: project-index cache accounting for this run ({"hits": n, "misses": m});
    #: the benchmark lane reports these to pin the incremental contract
    index_stats: "Dict[str, int]" = dataclasses.field(default_factory=dict)
    #: findings absorbed by a ``--baseline`` file (known debt, not new): kept
    #: out of ``findings`` so they do not fail the gate, reported separately
    baselined: "List[Finding]" = dataclasses.field(default_factory=list)
    #: True once :func:`apply_baseline` ran — SARIF then annotates every
    #: result with ``baselineState`` (new vs unchanged)
    baseline_applied: bool = False
    #: (rule, path, line, col) of suppressed findings silenced by a file-level
    #: ``disable-file`` comment rather than a per-line one — SARIF suppression
    #: records name the mechanism so dashboards can audit each budget
    file_suppressed_keys: "set" = dataclasses.field(default_factory=set)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def counts(self) -> "Dict[str, int]":
        out: "Dict[str, int]" = {}
        for finding in self.findings:
            out[finding.rule] = out.get(finding.rule, 0) + 1
        return out

    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 0 if not self.findings else 1


def iter_py_files(paths: "Sequence[str | Path]") -> "List[Path]":
    """Expand files/directories into a sorted, deduplicated .py file list."""
    seen: "Dict[Path, None]" = {}
    for raw in paths:
        path = Path(raw)
        if path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                if "__pycache__" not in sub.parts and ".git" not in sub.parts:
                    seen.setdefault(sub, None)
        elif path.suffix == ".py":
            seen.setdefault(path, None)
        elif not path.exists():
            raise FileNotFoundError(f"lint path does not exist: {path}")
    return sorted(seen)


def _suppressions(source: str) -> "Dict[int, set]":
    """Map of 1-indexed line -> rule ids (or {"ALL"}) disabled on that line."""
    out: "Dict[int, set]" = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        match = _SUPPRESS_RE.search(line)
        if match is None:
            continue
        ids = {part.strip().upper() for part in match.group(1).split(",") if part.strip()}
        out[lineno] = ids
    return out


def _file_suppressions(source: str) -> "set":
    """Rule ids (or {"ALL"}) disabled for the whole file via
    ``# tpu-lint: disable-file=...`` within the first five lines."""
    out: "set" = set()
    for line in source.splitlines()[:_FILE_SUPPRESS_WINDOW]:
        match = _FILE_SUPPRESS_RE.search(line)
        if match is not None:
            out |= {
                part.strip().upper() for part in match.group(1).split(",") if part.strip()
            }
    return out


def _select_rules(
    select: "Optional[Iterable[str]]" = None, ignore: "Optional[Iterable[str]]" = None
) -> "List[Rule]":
    rules = all_rules()
    known = {rule.id for rule in rules}
    for group, ids in (("select", select), ("ignore", ignore)):
        unknown = {i.upper() for i in ids or ()} - known
        if unknown:
            raise ValueError(f"unknown rule id(s) in --{group}: {', '.join(sorted(unknown))}")
    if select:
        wanted = {i.upper() for i in select}
        rules = [rule for rule in rules if rule.id in wanted]
    if ignore:
        dropped = {i.upper() for i in ignore}
        rules = [rule for rule in rules if rule.id not in dropped]
    return rules


def run_lint(
    paths: "Sequence[str | Path]",
    *,
    select: "Optional[Iterable[str]]" = None,
    ignore: "Optional[Iterable[str]]" = None,
    only: "Optional[Sequence[str | Path]]" = None,
) -> LintResult:
    """Lint ``paths`` (files and/or directory trees) with the selected rules.

    The project index is always built over ALL of ``paths`` (interprocedural
    facts must be whole-program to be true); ``only`` restricts which files'
    findings are REPORTED — the ``--changed-only`` fast path — without
    shrinking what the index sees. This is the library surface the tier-1
    gate calls (``run_lint(["unionml_tpu"])`` must be clean); the CLI in
    :func:`main` is a thin reporter over it.
    """
    from unionml_tpu.analysis.project import build_index

    rules = _select_rules(select, ignore)
    result = LintResult()
    files = iter_py_files(paths)
    index, parse_errors, stats = build_index(files)
    result.errors.extend(parse_errors)
    result.index_stats = stats
    only_set: "Optional[set]" = None
    if only is not None:
        only_set = {str(Path(p).resolve()) for p in only}
    summaries = sorted(index.by_path.values(), key=lambda s: s.path)

    def reported(path: str) -> bool:
        return only_set is None or str(Path(path).resolve()) in only_set

    def place(finding: Finding, disabled: "Dict[int, set]", file_disabled: "set") -> None:
        if finding.rule in file_disabled or "ALL" in file_disabled:
            result.suppressed.append(finding)
            result.file_suppressed_keys.add(
                (finding.rule, finding.path, finding.line, finding.col)
            )
            return
        ids = disabled.get(finding.line, ())
        if finding.rule in ids or "ALL" in ids:
            result.suppressed.append(finding)
        else:
            result.findings.append(finding)

    for summary in summaries:
        if not reported(summary.path):
            continue
        result.files += 1
        for rule in rules:
            # per-file rules are pure functions of (tree, path): their output
            # is memoized on the summary, which the index invalidates on any
            # content change — a warm run re-checks only edited files
            cached = summary.rule_findings.get(rule.id)
            if cached is None:
                cached = rule.check(summary.tree, summary.path)
                summary.rule_findings[rule.id] = cached
            for finding in cached:
                place(finding, summary.suppressions, summary.file_suppressions)

    # whole-program pass: every rule gets the index; findings land in the
    # file they point at, under that file's suppression comments
    for rule in rules:
        for finding in rule.check_project(index):
            if not reported(finding.path):
                continue
            owner = index.by_path.get(finding.path)
            place(
                finding,
                owner.suppressions if owner is not None else {},
                owner.file_suppressions if owner is not None else set(),
            )

    result.findings = _dedupe(result.findings)
    result.suppressed = _dedupe(result.suppressed)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def _dedupe(findings: "List[Finding]") -> "List[Finding]":
    """Drop repeats on (rule, path, line, col): a project rule re-deriving an
    intra-module finding (TPU001/TPU002's upgraded reachability covers the
    per-file rule's ground on its way across modules) reports it once."""
    seen: "Dict[Tuple[str, str, int, int], None]" = {}
    out: "List[Finding]" = []
    for finding in findings:
        key = (finding.rule, finding.path, finding.line, finding.col)
        if key in seen:
            continue
        seen[key] = None
        out.append(finding)
    return out


# ------------------------------------------------------------------- baseline
#
# A baseline is a JSON multiset of known findings. Entries are keyed on
# (rule, path, message) — deliberately NOT line/col, so unrelated edits that
# shift a known finding up or down the file do not resurface it — with a
# count, so introducing a SECOND instance of an already-baselined finding in
# the same file still fails the gate.


def write_baseline(result: LintResult, path: "str | Path") -> None:
    """Record ``result``'s active findings as the new baseline at ``path``."""
    counts: "Dict[Tuple[str, str, str], int]" = {}
    for finding in result.findings:
        key = (finding.rule, finding.path, finding.message)
        counts[key] = counts.get(key, 0) + 1
    payload = {
        "version": 1,
        "entries": [
            {"rule": rule, "path": fpath, "message": message, "count": count}
            for (rule, fpath, message), count in sorted(counts.items())
        ],
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def load_baseline(path: "str | Path") -> "Dict[Tuple[str, str, str], int]":
    """Parse a baseline file into its (rule, path, message) -> count multiset."""
    try:
        payload = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise ValueError(f"unreadable baseline {path}: {exc}") from exc
    entries = payload.get("entries") if isinstance(payload, dict) else None
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path} has no 'entries' list")
    out: "Dict[Tuple[str, str, str], int]" = {}
    for entry in entries:
        key = (str(entry["rule"]), str(entry["path"]), str(entry["message"]))
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def apply_baseline(result: LintResult, baseline: "Dict[Tuple[str, str, str], int]") -> None:
    """Move findings matched by ``baseline`` from ``findings`` to ``baselined``
    (in place). Matching consumes baseline budget: the N+1th instance of a
    finding baselined N times is still new."""
    remaining = dict(baseline)
    fresh: "List[Finding]" = []
    for finding in result.findings:
        key = (finding.rule, finding.path, finding.message)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            result.baselined.append(finding)
        else:
            fresh.append(finding)
    result.findings = fresh
    result.baseline_applied = True


def render_text(result: LintResult, *, show_suppressed: bool = False) -> str:
    lines = [finding.render() for finding in result.findings]
    if show_suppressed:
        lines += [f"{finding.render()} [suppressed]" for finding in result.suppressed]
    for path, message in result.errors:
        lines.append(f"{path}: PARSE-ERROR {message}")
    summary = (
        f"{len(result.findings)} finding(s), {len(result.suppressed)} suppressed, "
        f"{result.files} file(s) checked"
    )
    if result.baseline_applied:
        summary += f", {len(result.baselined)} baselined"
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    """Stable JSON schema (version 1) — the benchmark lane and external CI
    consume this, so field names are a contract."""
    payload = {
        "version": 1,
        "files": result.files,
        "findings": [dataclasses.asdict(finding) for finding in result.findings],
        "suppressed": [dataclasses.asdict(finding) for finding in result.suppressed],
        "errors": [{"path": path, "message": message} for path, message in result.errors],
        "counts": result.counts(),
        "exit_code": result.exit_code(),
    }
    if result.baseline_applied:
        payload["baselined"] = [dataclasses.asdict(f) for f in result.baselined]
    return json.dumps(payload, indent=2)


def render_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 — the interchange schema CI annotation surfaces (GitHub
    code scanning, VS Code SARIF viewers) render natively. Active findings
    are ``warning``-level results; suppressed findings are carried with an
    ``inSource`` suppression record whose justification names the mechanism
    (per-line ``disable`` vs file-level ``disable-file``) so dashboards can
    audit each budget; baseline runs annotate every result's
    ``baselineState`` (``new`` vs ``unchanged``); parse errors surface as
    tool ``notifications``."""
    from unionml_tpu.analysis.rules import RULES

    def _result(
        finding: Finding, suppressed: bool, baseline_state: "Optional[str]" = None
    ) -> "Dict[str, object]":
        record: "Dict[str, object]" = {
            "ruleId": finding.rule,
            "level": "warning",
            "message": {"text": finding.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": finding.path},
                        "region": {
                            "startLine": finding.line,
                            # SARIF columns are 1-indexed; Finding.col is 0-indexed
                            "startColumn": finding.col + 1,
                        },
                    }
                }
            ],
        }
        if suppressed:
            key = (finding.rule, finding.path, finding.line, finding.col)
            mechanism = (
                "# tpu-lint: disable-file"
                if key in result.file_suppressed_keys
                else "# tpu-lint: disable"
            )
            record["suppressions"] = [{"kind": "inSource", "justification": mechanism}]
        if baseline_state is not None:
            record["baselineState"] = baseline_state
        return record

    state_new = "new" if result.baseline_applied else None
    state_old = "unchanged" if result.baseline_applied else None

    payload = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpu-lint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [
                            {"id": rule_id, "shortDescription": {"text": cls.title}}
                            for rule_id, cls in sorted(RULES.items())
                        ],
                    }
                },
                "results": [
                    _result(f, suppressed=False, baseline_state=state_new)
                    for f in result.findings
                ]
                + [
                    _result(f, suppressed=False, baseline_state=state_old)
                    for f in result.baselined
                ]
                + [_result(f, suppressed=True) for f in result.suppressed],
                "invocations": [
                    {
                        "executionSuccessful": not result.errors,
                        "toolExecutionNotifications": [
                            {"level": "error", "message": {"text": f"{path}: {message}"}}
                            for path, message in result.errors
                        ],
                    }
                ],
            }
        ],
    }
    return json.dumps(payload, indent=2)


def _changed_files(ref: str) -> "List[Path]":
    """Files named by ``git diff --name-only <ref>`` plus untracked .py files
    — the ``--changed-only`` pre-push scope. Git prints paths relative to the
    repository toplevel, so they are anchored there (the command may run from
    any subdirectory)."""
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"], capture_output=True, text=True
    )
    if top.returncode != 0:
        raise ValueError(
            f"--changed-only requires a git checkout: {top.stderr.strip() or 'git failed'}"
        )
    root = Path(top.stdout.strip())
    out: "List[Path]" = []
    for args in (
        ["git", "diff", "--name-only", ref],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(args, capture_output=True, text=True)
        if proc.returncode != 0:
            raise ValueError(f"`{' '.join(args)}` failed: {proc.stderr.strip()}")
        out.extend(root / line for line in proc.stdout.splitlines() if line.endswith(".py"))
    return out


def main(argv: "Optional[Sequence[str]]" = None) -> int:
    """``python -m unionml_tpu.analysis [paths]`` entry point (also backs the
    ``unionml-tpu lint`` CLI command)."""
    parser = argparse.ArgumentParser(
        prog="tpu-lint",
        description="TPU/concurrency-aware static analyzer (rules TPU001-TPU019)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: the installed unionml_tpu package tree)",
    )
    parser.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    parser.add_argument("--select", default=None, help="comma-separated rule ids to run")
    parser.add_argument("--ignore", default=None, help="comma-separated rule ids to skip")
    parser.add_argument(
        "--show-suppressed", action="store_true", help="list suppressed findings in text output"
    )
    parser.add_argument(
        "--changed-only",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="report findings only for files in `git diff --name-only REF` (default HEAD) "
        "plus untracked files; the project index is still built over all PATHS",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help="JSON baseline of known findings: matched findings are reported as "
        "baselined (and do not fail the gate), only new ones count; "
        "composes with --changed-only and --format sarif (baselineState)",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="record the run's findings to --baseline FILE (then report zero new)",
    )
    args = parser.parse_args(argv)
    if args.update_baseline and not args.baseline:
        print("tpu-lint: --update-baseline requires --baseline FILE", file=sys.stderr)
        return 2
    # no paths: lint the package itself, wherever it is installed — so
    # `python -m unionml_tpu.analysis` works from any working directory
    paths = args.paths or [Path(__file__).resolve().parents[1]]
    split = lambda raw: [part.strip() for part in raw.split(",") if part.strip()] if raw else None
    try:
        only = _changed_files(args.changed_only) if args.changed_only else None
        result = run_lint(paths, select=split(args.select), ignore=split(args.ignore), only=only)
        if args.baseline:
            if args.update_baseline:
                write_baseline(result, args.baseline)
            elif not Path(args.baseline).exists():
                raise ValueError(
                    f"baseline {args.baseline} does not exist "
                    "(record one with --update-baseline)"
                )
            apply_baseline(result, load_baseline(args.baseline))
    except (FileNotFoundError, ValueError, OSError) as exc:
        print(f"tpu-lint: {exc}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(result))
    elif args.format == "sarif":
        print(render_sarif(result))
    else:
        print(render_text(result, show_suppressed=args.show_suppressed))
    return result.exit_code()
