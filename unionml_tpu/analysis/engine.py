"""tpu-lint rule engine: walk files, dispatch rules, report findings.

The repo's only static gate used to be the ``compileall`` syntax check
(tests/unit/test_syntax.py) — which exists because a trivially lintable
f-string bug once broke docs collection. The hazards that actually cost TPU
time are semantic, not syntactic: a host sync inside a jitted function turns
an async dispatch into a device round-trip, a donated buffer read after the
call is a use-after-free, an unlocked cross-thread attribute mutation is a
race that only fires under production load. Each is mechanically visible in
the AST; this engine makes them review-time failures instead of TPU-time
mysteries (the same layering JAX's own lint/pytype gates give the upstream
stack).

Architecture: one :func:`ast.parse` per file, every selected rule visits the
same tree (rules are stateless classes with a ``check(tree, path)`` method),
findings funnel through per-line ``# tpu-lint: disable=RULE`` suppressions
into a :class:`LintResult`. Reporters render text (``path:line: RULE id:
message``) or a stable JSON schema (``{"findings": [...], "counts": ...}``)
that the benchmark lane tracks across rounds. Exit codes: 0 clean (justified
suppressions included), 1 findings, 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "all_rules",
    "main",
    "render_json",
    "render_text",
    "run_lint",
]

#: ``# tpu-lint: disable=TPU001`` or ``disable=TPU001,TPU003`` or ``disable=all``,
#: anywhere on the offending line (typically a trailing comment)
_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, addressable as ``path:line``."""

    rule: str  #: rule id, e.g. "TPU003"
    path: str  #: file path as given to the walker
    line: int  #: 1-indexed source line
    col: int  #: 0-indexed column
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule:
    """Base class for tpu-lint rules.

    Subclasses set ``id``/``title`` and implement :meth:`check`. Rules are
    stateless across files — the engine instantiates each once per run and
    calls ``check`` per file, so a rule must not carry per-file state between
    calls (everything it needs is derivable from the tree).
    """

    id: str = ""
    title: str = ""

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        raise NotImplementedError

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


def all_rules() -> "List[Rule]":
    """Fresh instances of every registered rule, in id order."""
    from unionml_tpu.analysis.rules import RULES

    return [cls() for _, cls in sorted(RULES.items())]


@dataclasses.dataclass
class LintResult:
    """What a lint run produced: active findings, suppressed findings, errors."""

    findings: "List[Finding]" = dataclasses.field(default_factory=list)
    suppressed: "List[Finding]" = dataclasses.field(default_factory=list)
    #: files that failed to parse (path, message) — reported and exit-coded 2,
    #: since an unparseable file is a gate failure of its own
    errors: "List[Tuple[str, str]]" = dataclasses.field(default_factory=list)
    files: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def counts(self) -> "Dict[str, int]":
        out: "Dict[str, int]" = {}
        for finding in self.findings:
            out[finding.rule] = out.get(finding.rule, 0) + 1
        return out

    def exit_code(self) -> int:
        if self.errors:
            return 2
        return 0 if not self.findings else 1


def iter_py_files(paths: "Sequence[str | Path]") -> "List[Path]":
    """Expand files/directories into a sorted, deduplicated .py file list."""
    seen: "Dict[Path, None]" = {}
    for raw in paths:
        path = Path(raw)
        if path.is_dir():
            for sub in sorted(path.rglob("*.py")):
                if "__pycache__" not in sub.parts and ".git" not in sub.parts:
                    seen.setdefault(sub, None)
        elif path.suffix == ".py":
            seen.setdefault(path, None)
        elif not path.exists():
            raise FileNotFoundError(f"lint path does not exist: {path}")
    return sorted(seen)


def _suppressions(source: str) -> "Dict[int, set]":
    """Map of 1-indexed line -> rule ids (or {"ALL"}) disabled on that line."""
    out: "Dict[int, set]" = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        match = _SUPPRESS_RE.search(line)
        if match is None:
            continue
        ids = {part.strip().upper() for part in match.group(1).split(",") if part.strip()}
        out[lineno] = ids
    return out


def _select_rules(
    select: "Optional[Iterable[str]]" = None, ignore: "Optional[Iterable[str]]" = None
) -> "List[Rule]":
    rules = all_rules()
    known = {rule.id for rule in rules}
    for group, ids in (("select", select), ("ignore", ignore)):
        unknown = {i.upper() for i in ids or ()} - known
        if unknown:
            raise ValueError(f"unknown rule id(s) in --{group}: {', '.join(sorted(unknown))}")
    if select:
        wanted = {i.upper() for i in select}
        rules = [rule for rule in rules if rule.id in wanted]
    if ignore:
        dropped = {i.upper() for i in ignore}
        rules = [rule for rule in rules if rule.id not in dropped]
    return rules


def run_lint(
    paths: "Sequence[str | Path]",
    *,
    select: "Optional[Iterable[str]]" = None,
    ignore: "Optional[Iterable[str]]" = None,
) -> LintResult:
    """Lint ``paths`` (files and/or directory trees) with the selected rules.

    This is the library surface the tier-1 gate calls (``run_lint(["unionml_tpu"])``
    must be clean); the CLI in :func:`main` is a thin reporter over it.
    """
    rules = _select_rules(select, ignore)
    result = LintResult()
    for path in iter_py_files(paths):
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError, ValueError) as exc:
            result.errors.append((str(path), str(exc)))
            continue
        result.files += 1
        disabled = _suppressions(source)
        for rule in rules:
            for finding in rule.check(tree, str(path)):
                ids = disabled.get(finding.line, ())
                if finding.rule in ids or "ALL" in ids:
                    result.suppressed.append(finding)
                else:
                    result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def render_text(result: LintResult, *, show_suppressed: bool = False) -> str:
    lines = [finding.render() for finding in result.findings]
    if show_suppressed:
        lines += [f"{finding.render()} [suppressed]" for finding in result.suppressed]
    for path, message in result.errors:
        lines.append(f"{path}: PARSE-ERROR {message}")
    summary = (
        f"{len(result.findings)} finding(s), {len(result.suppressed)} suppressed, "
        f"{result.files} file(s) checked"
    )
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    """Stable JSON schema (version 1) — the benchmark lane and external CI
    consume this, so field names are a contract."""
    payload = {
        "version": 1,
        "files": result.files,
        "findings": [dataclasses.asdict(finding) for finding in result.findings],
        "suppressed": [dataclasses.asdict(finding) for finding in result.suppressed],
        "errors": [{"path": path, "message": message} for path, message in result.errors],
        "counts": result.counts(),
        "exit_code": result.exit_code(),
    }
    return json.dumps(payload, indent=2)


def main(argv: "Optional[Sequence[str]]" = None) -> int:
    """``python -m unionml_tpu.analysis [paths]`` entry point (also backs the
    ``unionml-tpu lint`` CLI command)."""
    parser = argparse.ArgumentParser(
        prog="tpu-lint",
        description="TPU/concurrency-aware static analyzer (rules TPU001-TPU009)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: the installed unionml_tpu package tree)",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--select", default=None, help="comma-separated rule ids to run")
    parser.add_argument("--ignore", default=None, help="comma-separated rule ids to skip")
    parser.add_argument(
        "--show-suppressed", action="store_true", help="list suppressed findings in text output"
    )
    args = parser.parse_args(argv)
    # no paths: lint the package itself, wherever it is installed — so
    # `python -m unionml_tpu.analysis` works from any working directory
    paths = args.paths or [Path(__file__).resolve().parents[1]]
    split = lambda raw: [part.strip() for part in raw.split(",") if part.strip()] if raw else None
    try:
        result = run_lint(paths, select=split(args.select), ignore=split(args.ignore))
    except (FileNotFoundError, ValueError) as exc:
        print(f"tpu-lint: {exc}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(result))
    else:
        print(render_text(result, show_suppressed=args.show_suppressed))
    return result.exit_code()
