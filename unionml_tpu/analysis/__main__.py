"""``python -m unionml_tpu.analysis [paths] [--format json] [--select ...]``."""

import sys

from unionml_tpu.analysis.engine import main

if __name__ == "__main__":
    sys.exit(main())
