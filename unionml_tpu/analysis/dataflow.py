"""Forward dataflow over :mod:`unionml_tpu.analysis.cfg` graphs.

The framework is deliberately small: a gen/kill lattice over sets of hashable
facts, a worklist solver, and per-node IN maps.  Two join modes cover every
rule built so far:

* **may** (set union, the default) — "does *some* path carry this fact here?"
  Used by the resource-leak family (TPU016/TPU017/TPU019), lock-across-yield
  (TPU018) and the path-sensitive use-after-donate upgrade (TPU002).
* **must** (set intersection) — "does *every* path carry it?"  Used by
  :func:`dominators`, which TPU015 uses to accept a retry bound only when the
  bound test dominates the loop back edge.

Transfer functions are *edge-aware*.  A problem describes three things:

* :meth:`Problem.gen_kill` — the facts a node generates and kills when it
  completes **normally**.
* exception edges apply only the kills (``out = in - kill``): if the
  acquiring statement itself raised, the acquisition never happened, while a
  release that raises has still released.
* :meth:`Problem.assume` — an optional filter applied on ``true``/``false``
  branch edges, giving cheap path sensitivity (e.g. "on the branch where
  ``retry_after is not None`` the charge did not happen").

Facts are opaque hashable values; rules use tuples like
``(var, protocol, line)``.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, FrozenSet, Hashable, Optional, Set, Tuple

from unionml_tpu.analysis.cfg import BACK, CFG, EXC, FALSE, TRUE, CFGNode

__all__ = ["Problem", "Solution", "solve_forward", "dominators"]

Fact = Hashable
Facts = FrozenSet[Fact]

EMPTY: Facts = frozenset()


class Problem:
    """Base class for forward gen/kill dataflow problems."""

    #: union join when True (may-analysis), intersection when False (must).
    may = True

    #: When False (default), exception edges apply only kills: if the node
    #: itself raised, its acquisitions never happened.  Problems tracking
    #: "was this node executed" (dominators) set True.
    gen_on_exc = False

    def entry_facts(self, cfg: CFG) -> Facts:
        return EMPTY

    def gen_kill(self, node: CFGNode) -> Tuple[Set[Fact], Set[Fact]]:
        """Facts generated / killed when ``node`` completes normally."""
        return set(), set()

    def apply_kill(self, facts: Set[Fact], kill: Set[Fact]) -> Set[Fact]:
        """How kills match facts.  Default: exact-element set difference.
        Problems whose facts carry provenance (e.g. the acquisition line)
        override this to match on a prefix."""
        return facts - kill

    def assume(self, node: CFGNode, branch: str, facts: Facts) -> Facts:
        """Refine ``facts`` along a ``true``/``false`` edge out of ``node``."""
        return facts

    # Iteration bound; CFGs are per-function so this is generous.
    max_iterations = 100000


class Solution:
    """Per-node IN sets plus the facts reaching the synthetic exits."""

    def __init__(self, cfg: CFG, ins: Dict[int, Optional[Facts]]) -> None:
        self.cfg = cfg
        self._ins = ins

    def in_facts(self, nid: int) -> Facts:
        facts = self._ins.get(nid)
        return EMPTY if facts is None else facts

    def reachable(self, nid: int) -> bool:
        return self._ins.get(nid) is not None

    @property
    def at_raise(self) -> Facts:
        return self.in_facts(self.cfg.raise_node)

    @property
    def at_exit(self) -> Facts:
        return self.in_facts(self.cfg.exit)


def _edge_out(problem: Problem, node: CFGNode, in_facts: Facts, kind: str) -> Facts:
    gen, kill = problem.gen_kill(node)
    base = problem.apply_kill(set(in_facts), kill) if kill else set(in_facts)
    if kind == EXC and not problem.gen_on_exc:
        out: Facts = frozenset(base)
    else:
        out = frozenset(base | gen)
        if kind in (TRUE, FALSE):
            out = frozenset(problem.assume(node, kind, out))
    return out


def solve_forward(cfg: CFG, problem: Problem) -> Solution:
    """Iterate the worklist to a fixed point; ``None`` IN means unreachable."""
    ins: Dict[int, Optional[Facts]] = {nid: None for nid in cfg.nodes}
    ins[cfg.entry] = frozenset(problem.entry_facts(cfg))
    work = deque([cfg.entry])
    queued = {cfg.entry}
    iterations = 0
    while work:
        iterations += 1
        if iterations > problem.max_iterations:  # pragma: no cover - safety net
            break
        nid = work.popleft()
        queued.discard(nid)
        node = cfg.nodes[nid]
        in_facts = ins[nid]
        if in_facts is None:
            continue
        for succ, kind in node.succs:
            out = _edge_out(problem, node, in_facts, kind)
            old = ins[succ]
            if old is None:
                new = out
            elif problem.may:
                new = old | out
            else:
                new = old & out
            if new != old:
                ins[succ] = new
                if succ not in queued:
                    queued.add(succ)
                    work.append(succ)
    return Solution(cfg, ins)


class _Dominators(Problem):
    may = False  # intersection: a node dominates iff it is on *every* path
    gen_on_exc = True  # a raising node was still executed on that path

    def entry_facts(self, cfg: CFG) -> Facts:
        return frozenset({cfg.entry})

    def gen_kill(self, node: CFGNode):  # type: ignore[override]
        return {node.nid}, set()


def dominators(cfg: CFG) -> Dict[int, FrozenSet[int]]:
    """Map node id -> set of dominator node ids (reflexive).

    Computed as a must-forward problem: IN[n] = ∩ over preds of (IN[p] ∪ {p}),
    so ``d in dominators(cfg)[n]`` iff every path from entry to ``n`` passes
    through ``d``.  Unreachable nodes map to the empty set.
    """
    sol = solve_forward(cfg, _Dominators())
    out: Dict[int, FrozenSet[int]] = {}
    for nid in cfg.nodes:
        if sol.reachable(nid):
            out[nid] = frozenset(sol.in_facts(nid) | {nid})
        else:
            out[nid] = frozenset()
    return out
