"""Whole-program project index for tpu-lint's interprocedural rules.

The per-file rules see one ``ast.parse`` tree and nothing else, so TPU001's
jit-reachability stops at module boundaries and whole hazard classes — a lock
acquired in ``replicas.py`` while a ``continuous.py`` lock is held, a
recompile storm at a call site two modules away from the ``jax.jit`` wrap, an
executor target that reads a tenancy contextvar through three helper calls —
are structurally invisible. This module builds the missing layer: **one pass
over every file** resolves imports to modules, assembles a cross-module symbol
table, class hierarchy, and call graph, and records per-function facts (locks
acquired via ``with self.<lock>:`` and the ``*_locked`` convention, jit-entry
status and static-argument positions, contextvar reads, executor/thread
submissions). Rules that implement ``check_project(index)`` (the second rule
protocol in :mod:`unionml_tpu.analysis.engine`) query the index instead of a
single tree — the same shape Meta's Infer/RacerD use for interprocedural lock
analysis.

The index is **content-hash cached and incremental**: each file's summary
(including its parsed tree) is keyed on a SHA-256 of its bytes in a
process-global cache, so a warm :func:`unionml_tpu.analysis.engine.run_lint`
re-summarizes only edited files and the tier-1 analysis gate stays inside its
5 s budget as the tree grows. :func:`clear_index_cache` drops the cache (the
benchmark lane uses it to measure cold vs warm cost).

Everything here is stdlib-only and purely syntactic — no imports of the
analyzed code are ever executed.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from unionml_tpu.analysis.rules._common import (
    LOCK_FACTORIES,
    call_target,
    dotted,
    is_jit_decorator,
    jit_wrap_call,
    literal_argnums,
)

__all__ = [
    "CallSite",
    "ClassFacts",
    "ExecutorCall",
    "FunctionFacts",
    "JitBinding",
    "ModuleSummary",
    "ProjectIndex",
    "build_index",
    "clear_index_cache",
    "function_cfg",
]

#: raw lock tokens: ``self.<attr>`` for instance locks, ``mod:<name>`` for
#: module-level locks — resolved to global lock node ids by the index
_MOD_LOCK_PREFIX = "mod:"

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call expression inside a function body."""

    raw: str  #: dotted target as written ("helper", "mod.helper", "self.x.f")
    line: int
    held: Tuple[str, ...]  #: raw lock tokens held at the call site


@dataclasses.dataclass(frozen=True)
class ExecutorCall:
    """A ``run_in_executor``/``submit``/``threading.Thread`` submission."""

    kind: str  #: "executor" (run_in_executor/submit) or "thread"
    target_raw: Optional[str]  #: dotted callable, None when unresolvable
    line: int
    wrapped: bool  #: already routed through contextvars ``ctx.run``
    lambda_calls: Tuple[str, ...] = ()  #: call targets inside a lambda target


@dataclasses.dataclass(frozen=True)
class JitBinding:
    """A name that, when called, invokes a jit-compiled program."""

    binding: str  #: how call sites spell it ("self._decode", "step", ...)
    target_raw: Optional[str]  #: the wrapped function, as written
    static_argnums: Tuple[int, ...]
    static_argnames: Tuple[str, ...]
    donate_argnums: Tuple[int, ...]
    line: int
    cls: Optional[str]  #: owning class for "self." bindings


@dataclasses.dataclass
class FunctionFacts:
    """Per-function facts recorded in the one indexing pass."""

    module: str
    cls: Optional[str]
    name: str
    qualname: str  #: "name" or "Class.name" (module-local key)
    path: str
    line: int
    node: ast.AST
    params: Tuple[str, ...] = ()
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    #: (raw lock token, line, raw locks already held at that point)
    acquisitions: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(default_factory=list)
    #: raw receivers of ``<recv>.get(...)`` calls (candidate contextvar reads)
    cv_reads: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    executor_calls: List[ExecutorCall] = dataclasses.field(default_factory=list)
    jit_entry: bool = False
    #: local/param names with an inferable class type (raw dotted class name)
    local_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: names assigned from contextvars.copy_context() in this function
    ctx_names: Set[str] = dataclasses.field(default_factory=set)

    @property
    def fq(self) -> str:
        return f"{self.module}:{self.qualname}"


@dataclasses.dataclass
class ClassFacts:
    name: str
    module: str
    bases: Tuple[str, ...] = ()  #: raw dotted base names, resolved lazily
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: self.<attr> -> raw dotted class name of the constructor/annotation
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Set[str] = dataclasses.field(default_factory=set)

    def primary_lock(self) -> Optional[str]:
        """The lock a ``*_locked`` method of this class is assumed to hold:
        ``_lock`` when present (the repo-wide convention), else the class's
        single lock, else None (ambiguous — never guessed)."""
        if "_lock" in self.lock_attrs:
            return "_lock"
        if len(self.lock_attrs) == 1:
            return next(iter(self.lock_attrs))
        return None


@dataclasses.dataclass
class ModuleSummary:
    """Everything the project rules need from one file."""

    path: str
    module: str
    tree: ast.Module
    source: str
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    functions: Dict[str, FunctionFacts] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassFacts] = dataclasses.field(default_factory=dict)
    module_locks: Set[str] = dataclasses.field(default_factory=set)
    contextvars: Set[str] = dataclasses.field(default_factory=set)
    jit_bindings: List[JitBinding] = dataclasses.field(default_factory=list)
    #: module-level donor callables -> literal donated positions
    donors: Dict[str, Tuple[int, ...]] = dataclasses.field(default_factory=dict)
    suppressions: Dict[int, Set[str]] = dataclasses.field(default_factory=dict)
    #: rule ids (or {"ALL"}) disabled for the whole file via a
    #: ``# tpu-lint: disable-file=...`` comment in the first five lines
    file_suppressions: Set[str] = dataclasses.field(default_factory=set)
    #: per-file rule findings memo, keyed by rule id — per-file rules are pure
    #: functions of (tree, path), so their output is valid as long as the
    #: content hash matches; the engine consults this to skip re-checks on
    #: warm runs (cleared with the summary on any edit)
    rule_findings: Dict[str, list] = dataclasses.field(default_factory=dict)
    #: per-function CFG memo keyed by (qualname, line) — CFGs are pure
    #: functions of the AST, so like ``rule_findings`` they live exactly as
    #: long as the content-hashed summary (see :func:`function_cfg`)
    cfgs: Dict[Tuple[str, int], object] = dataclasses.field(default_factory=dict)
    #: per-function prescan memo for the flow rules (TPU016-TPU019): which
    #: protocols/locks/yields a function mentions at all, so warm project
    #: passes skip CFG construction and dataflow for the ~95% of functions
    #: that touch none of them
    flow_hints: Dict[Tuple[str, int], object] = dataclasses.field(default_factory=dict)


# --------------------------------------------------------------------- naming


def module_name_for(path: Path) -> str:
    """Dotted module name for ``path``, walking up while ``__init__.py``
    exists (loose files — test fixtures — get their bare stem)."""
    path = path.resolve()
    parts: List[str] = [] if path.stem == "__init__" else [path.stem]
    parent = path.parent
    while (parent / "__init__.py").exists():
        parts.insert(0, parent.name)
        parent = parent.parent
    return ".".join(parts) or path.stem


# ------------------------------------------------------------- summary build


def _lambda_call_targets(node: ast.Lambda) -> Tuple[str, ...]:
    out: List[str] = []
    for child in ast.walk(node):
        if isinstance(child, ast.Call):
            target = call_target(child)
            if target:
                out.append(target)
    return tuple(out)


class _FunctionWalker:
    """Walks one function body recording calls, lock acquisitions (with the
    held-set at each point), contextvar-read candidates, executor/thread
    submissions, local type hints, copy_context() bindings, and jit-wrap
    assignments — ONE traversal per function (the index build is on the
    tier-1 gate's 5 s clock, so every fact rides the same pass). Nested
    defs/lambdas/classes are separate scopes: their statements are not
    charged to this function, and nested defs are handed back to the builder
    for their own FunctionFacts."""

    def __init__(self, builder: "_SummaryBuilder", facts: FunctionFacts, lock_attrs: Set[str], cls: Optional[str]):
        self.builder = builder
        self.facts = facts
        self.lock_attrs = lock_attrs
        self.cls = cls
        self.module_locks = builder.summary.module_locks

    def walk(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _visit(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        """Per-node dispatch, entered both from :meth:`walk` (children) and
        for each With-body statement — a ``with self._b:`` textually nested
        inside ``with self._a:`` must re-enter the With branch, or its
        acquisition (and the held-set under it) is silently lost."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.builder.visit_function(node, cls=None)
            return
        if isinstance(node, (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            self.builder.record_import(node)
            return
        if isinstance(node, ast.Assign):
            self._record_locals(node)
            self.builder.record_assign(node, self.cls)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                raw = self._lock_token(item.context_expr)
                if raw is not None:
                    self.facts.acquisitions.append((raw, node.lineno, inner))
                    inner = inner + (raw,)
            for item in node.items:  # guards/`as` targets may contain calls
                self._record(item.context_expr, held)
                self.walk(item.context_expr, held)
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        self._record(node, held)
        self.walk(node, held)

    def _record_locals(self, node: ast.Assign) -> None:
        """Local type hints (``x = ClassName(...)``) and copy_context names
        (``ctx = contextvars.copy_context()``), folded into the main walk."""
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        if isinstance(node.value, ast.Call):
            ctor = call_target(node.value)
            if ctor in ("contextvars.copy_context", "copy_context"):
                self.facts.ctx_names.add(name)
            # CapWord final segment — a constructor, not a factory function
            elif ctor and ctor.rsplit(".", 1)[-1][:1].isupper():
                self.facts.local_types.setdefault(name, ctor)

    def _lock_token(self, expr: ast.AST) -> Optional[str]:
        raw = dotted(expr)
        if raw is None:
            return None
        if raw.startswith(("self.", "cls.")):
            attr = raw.split(".", 1)[1]
            if "." not in attr and attr in self.lock_attrs:
                return f"self.{attr}"
        elif "." not in raw and raw in self.module_locks:
            return _MOD_LOCK_PREFIX + raw
        return None

    def _record(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if not isinstance(node, ast.Call):
            return
        target = call_target(node)
        if target is not None:
            self.facts.calls.append(CallSite(raw=target, line=node.lineno, held=held))
            # contextvar-read candidate: <recv>.get(...)
            if target.endswith(".get"):
                self.facts.cv_reads.append((target[: -len(".get")], node.lineno))
            # copy_context() binding: ctx = contextvars.copy_context()
        self._record_executor(node, target)

    def _record_executor(self, node: ast.Call, target: Optional[str]) -> None:
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        if attr == "run_in_executor" and len(node.args) >= 2:
            self._submission("executor", node, node.args[1])
        elif attr == "submit" and node.args:
            # `.submit` is overloaded in this codebase (the engine's stream
            # submission API takes a prompt, not a callable) — only receivers
            # that are recognizably thread/process pools count
            recv = dotted(func.value)
            last = (recv or "").rsplit(".", 1)[-1].lower()
            if "executor" in last or "pool" in last:
                self._submission("executor", node, node.args[0])
        elif target in ("threading.Thread", "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    self._submission("thread", node, kw.value)

    def _submission(self, kind: str, node: ast.Call, callable_expr: ast.AST) -> None:
        if isinstance(callable_expr, ast.Lambda):
            self.facts.executor_calls.append(
                ExecutorCall(
                    kind=kind,
                    target_raw=None,
                    line=node.lineno,
                    wrapped=False,
                    lambda_calls=_lambda_call_targets(callable_expr),
                )
            )
            return
        raw = dotted(callable_expr)
        wrapped = False
        if raw is not None and raw.endswith(".run"):
            base = raw[: -len(".run")]
            if base in self.facts.ctx_names or base in ("ctx", "context"):
                wrapped = True
        # functools.partial(ctx.run, fn, ...) as the submitted callable
        if isinstance(callable_expr, ast.Call) and call_target(callable_expr) in (
            "partial",
            "functools.partial",
        ):
            if callable_expr.args:
                first = dotted(callable_expr.args[0])
                if first is not None and first.endswith(".run"):
                    wrapped = True
                elif len(callable_expr.args) >= 1:
                    raw = first
        self.facts.executor_calls.append(
            ExecutorCall(kind=kind, target_raw=raw, line=node.lineno, wrapped=wrapped)
        )


def _params_of(func_node: ast.AST) -> Tuple[str, ...]:
    args = func_node.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return tuple(names)


def _static_positions(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...], Tuple[int, ...]]:
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    donate: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = literal_argnums(kw.value) or ()
        elif kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                names = (kw.value.value,)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                names = tuple(
                    e.value for e in kw.value.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
        elif kw.arg == "donate_argnums":
            donate = literal_argnums(kw.value) or ()
    return nums, names, donate


def build_summary(path: Path, source: str, tree: ast.Module) -> ModuleSummary:
    """One fused pass over ``tree`` extracting every fact the project rules
    use (imports, defs, locks, contextvars, jit bindings, executor calls) —
    the build rides the tier-1 gate's clock, so nothing walks the tree
    twice except the per-class attribute pre-scan (lock attributes must be
    known before the class's methods are walked, wherever ``__init__`` sits)."""
    from unionml_tpu.analysis.engine import (  # shared comment grammar
        _file_suppressions,
        _suppressions,
    )

    module = module_name_for(path)
    summary = ModuleSummary(
        path=str(path),
        module=module,
        tree=tree,
        source=source,
        suppressions=_suppressions(source),
        file_suppressions=_file_suppressions(source),
    )
    _SummaryBuilder(summary, is_pkg=path.name == "__init__.py").run()
    return summary


class _SummaryBuilder:
    def __init__(self, summary: ModuleSummary, is_pkg: bool):
        self.summary = summary
        module = summary.module
        self._pkg_parts = module.split(".") if is_pkg else module.split(".")[:-1]
        #: (cls-or-None, bare name) of functions jit-wrapped by assignment,
        #: marked jit_entry after the full pass (the def may come later)
        self._pending_marks: List[Tuple[Optional[str], str]] = []

    def run(self) -> None:
        tree = self.summary.tree
        # module-level locks and contextvars (top level only: a lock behind an
        # `if` is still module-global; one inside a function is not)
        for node in tree.body:
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = call_target(node.value)
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                if ctor in LOCK_FACTORIES:
                    self.summary.module_locks.add(target.id)
                elif ctor in ("contextvars.ContextVar", "ContextVar"):
                    self.summary.contextvars.add(target.id)
        self.visit_body(tree, cls=None)
        for cls, bare in self._pending_marks:
            facts = self.summary.functions.get(f"{cls}.{bare}" if cls else bare)
            if facts is not None:
                facts.jit_entry = True

    # ------------------------------------------------------------ traversal

    def visit_body(self, node: ast.AST, cls: Optional[str]) -> None:
        """Module/class-level recursion; function bodies hand off to
        :class:`_FunctionWalker` (one traversal each)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                self.record_import(child)
            elif isinstance(child, ast.ClassDef):
                facts = ClassFacts(
                    name=child.name,
                    module=self.summary.module,
                    bases=tuple(b for b in (dotted(base) for base in child.bases) if b),
                )
                self.summary.classes[child.name] = facts
                _scan_class_attrs(facts, child)
                self.visit_body(child, cls=child.name)
            elif isinstance(child, _FUNC_NODES):
                self.visit_function(child, cls)
            else:
                if isinstance(child, ast.Assign):
                    self.record_assign(child, cls)
                self.visit_body(child, cls)

    def visit_function(self, func_node: ast.AST, cls: Optional[str]) -> None:
        summary = self.summary
        qualname = f"{cls}.{func_node.name}" if cls else func_node.name
        local_types: Dict[str, str] = {}
        args = func_node.args
        for arg in args.posonlyargs + args.args + args.kwonlyargs:
            if arg.annotation is not None:
                raw = dotted(arg.annotation)
                if raw:
                    local_types[arg.arg] = raw
        facts = FunctionFacts(
            module=summary.module,
            cls=cls,
            name=func_node.name,
            qualname=qualname,
            path=summary.path,
            line=func_node.lineno,
            node=func_node,
            params=_params_of(func_node),
            jit_entry=False,
            local_types=local_types,
        )
        for dec in func_node.decorator_list:
            if not is_jit_decorator(dec):
                continue
            facts.jit_entry = True
            nums: Tuple[int, ...] = ()
            names: Tuple[str, ...] = ()
            donate: Tuple[int, ...] = ()
            if isinstance(dec, ast.Call):
                nums, names, donate = _static_positions(dec)
            if cls:
                # decorator argnums are relative to the UNBOUND function
                # (position 0 = self), but call sites spell `self.name(...)`
                # without the receiver — store call-site-relative positions
                nums = tuple(n - 1 for n in nums if n > 0)
                donate = tuple(n - 1 for n in donate if n > 0)
            binding = f"self.{facts.name}" if cls else facts.name
            summary.jit_bindings.append(
                JitBinding(
                    binding=binding,
                    target_raw=binding,
                    static_argnums=nums,
                    static_argnames=names,
                    donate_argnums=donate,
                    line=func_node.lineno,
                    cls=cls,
                )
            )
            if cls is None and donate:
                summary.donors[facts.name] = donate
        class_facts = summary.classes.get(cls) if cls else None
        lock_attrs = class_facts.lock_attrs if class_facts else set()
        # *_locked convention: the body runs with the class lock held
        held: Tuple[str, ...] = ()
        if cls and func_node.name.endswith("_locked") and class_facts is not None:
            primary = class_facts.primary_lock()
            if primary is not None:
                held = (f"self.{primary}",)
        summary.functions[qualname] = facts
        if class_facts is not None:
            class_facts.methods.add(func_node.name)
        _FunctionWalker(self, facts, lock_attrs, cls).walk(func_node, held)

    # ------------------------------------------------------------- recording

    def record_import(self, node: ast.AST) -> None:
        table = self.summary.imports
        if isinstance(node, ast.Import):
            for alias in node.names:
                # `import a.b.c` binds `a`, but call sites spell the full
                # dotted path — keep the full name resolvable
                table[alias.asname or alias.name] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                parts = self._pkg_parts
                base_parts = parts[: len(parts) - (node.level - 1)] if node.level > 1 else parts
                base = ".".join(base_parts + ([node.module] if node.module else []))
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                table[alias.asname or alias.name] = f"{base}.{alias.name}" if base else alias.name

    def record_assign(self, node: ast.Assign, cls: Optional[str]) -> None:
        """``<target> = jax.jit(fn, ...)`` bindings, wherever they appear
        (module level, class body, ``__init__``, a local helper scope)."""
        if len(node.targets) != 1:
            return
        wrap = jit_wrap_call(node.value)
        target = dotted(node.targets[0])
        if wrap is None or target is None or not wrap.args:
            return
        nums, names, donate = _static_positions(wrap)
        target_raw = dotted(wrap.args[0])
        if target.startswith(("self.", "cls.")):
            binding = "self." + target.split(".", 1)[1]
        else:
            binding = target
        self.summary.jit_bindings.append(
            JitBinding(
                binding=binding,
                target_raw=target_raw,
                static_argnums=nums,
                static_argnames=names,
                donate_argnums=donate,
                line=node.lineno,
                cls=cls,
            )
        )
        # mark the wrapped function as a jit entry for reachability rules
        if target_raw:
            if target_raw.startswith(("self.", "cls.")) and cls:
                self._pending_marks.append((cls, target_raw.split(".", 1)[1]))
            elif "." not in target_raw:
                self._pending_marks.append((None, target_raw))
        if cls is None and donate and "." not in binding:
            self.summary.donors[binding] = donate


def _scan_class_attrs(facts: ClassFacts, cls: ast.ClassDef) -> None:
    """Lock attributes and constructor-derived attribute types, anywhere in
    the class body (the TPU003/TPU007 discovery, widened with types)."""
    ann: Dict[str, str] = {}
    for method in cls.body:
        if not isinstance(method, _FUNC_NODES):
            continue
        for arg in method.args.posonlyargs + method.args.args + method.args.kwonlyargs:
            if arg.annotation is not None:
                raw = dotted(arg.annotation)
                if raw:
                    ann[arg.arg] = raw
    for node in ast.walk(cls):
        value = getattr(node, "value", None)
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target] if isinstance(node, ast.AnnAssign) else []
        )
        if not targets or value is None:
            continue
        for target in targets:
            raw = dotted(target)
            if raw is None or not raw.startswith(("self.", "cls.")):
                continue
            attr = raw.split(".", 1)[1]
            if "." in attr:
                continue
            if isinstance(value, ast.Call):
                ctor = call_target(value)
                if ctor in LOCK_FACTORIES:
                    facts.lock_attrs.add(attr)
                elif ctor and ctor.rsplit(".", 1)[-1][:1].isupper():
                    facts.attr_types.setdefault(attr, ctor)
            elif isinstance(value, ast.Name) and value.id in ann:
                # self._engine = engine   (param annotated with a class)
                facts.attr_types.setdefault(attr, ann[value.id])


# ----------------------------------------------------------------- the index


class ProjectIndex:
    """Cross-module symbol table + call graph over a set of summaries."""

    def __init__(self, summaries: "List[ModuleSummary]"):
        self.modules: Dict[str, ModuleSummary] = {}
        for summary in summaries:
            self.modules[summary.module] = summary
        self.by_path: Dict[str, ModuleSummary] = {s.path: s for s in summaries}
        self._acq_memo: Dict[str, Dict[str, Tuple[Tuple[str, ...], int]]] = {}

    # -- symbol resolution ---------------------------------------------------

    def iter_functions(self) -> "Iterable[FunctionFacts]":
        for summary in self.modules.values():
            yield from summary.functions.values()

    def resolve_class(self, raw: str, summary: ModuleSummary) -> Optional[ClassFacts]:
        """Resolve a raw dotted class name written in ``summary``'s module."""
        if raw in summary.classes:
            return summary.classes[raw]
        fq = self._resolve_alias(raw, summary)
        if fq is None:
            return None
        mod, _, sym = fq.rpartition(".")
        target = self.modules.get(mod)
        if target is not None and sym in target.classes:
            return target.classes[sym]
        return None

    def class_mro(self, facts: ClassFacts) -> "List[ClassFacts]":
        """BFS linearization over raw base names (cycles guarded)."""
        out: List[ClassFacts] = [facts]
        seen = {(facts.module, facts.name)}
        queue = [facts]
        while queue:
            current = queue.pop(0)
            summary = self.modules.get(current.module)
            if summary is None:
                continue
            for base_raw in current.bases:
                base = self.resolve_class(base_raw, summary)
                if base is not None and (base.module, base.name) not in seen:
                    seen.add((base.module, base.name))
                    out.append(base)
                    queue.append(base)
        return out

    def _resolve_alias(self, raw: str, summary: ModuleSummary) -> Optional[str]:
        """Map a raw dotted name through the module's import table (longest
        alias prefix wins)."""
        parts = raw.split(".")
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            if prefix in summary.imports:
                rest = parts[cut:]
                return ".".join([summary.imports[prefix]] + rest)
        return None

    def _lookup_fq(self, fq: str) -> Optional[FunctionFacts]:
        """``pkg.mod.sym`` or ``pkg.mod.Class.method`` -> FunctionFacts
        (constructors resolve to ``__init__``)."""
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            summary = self.modules.get(mod)
            if summary is None:
                continue
            sym = ".".join(parts[cut:])
            if sym in summary.functions:
                return summary.functions[sym]
            if sym in summary.classes:
                return self._method(summary.classes[sym], "__init__")
            if "." in sym:
                cls_name, meth = sym.split(".", 1)
                if cls_name in summary.classes and "." not in meth:
                    return self._method(summary.classes[cls_name], meth)
            return None
        return None

    def _method(self, cls: ClassFacts, name: str) -> Optional[FunctionFacts]:
        for candidate in self.class_mro(cls):
            summary = self.modules.get(candidate.module)
            if summary is None:
                continue
            facts = summary.functions.get(f"{candidate.name}.{name}")
            if facts is not None:
                return facts
        return None

    def resolve_call(
        self, raw: str, summary: ModuleSummary, caller: Optional[FunctionFacts] = None
    ) -> Optional[FunctionFacts]:
        """Best-effort resolution of a call target string to function facts.

        Handles: same-module functions and classes, ``self.method`` (through
        the class hierarchy), ``self.<attr>.method`` (through constructor /
        annotation attribute types), annotated-parameter and local-constructor
        variables, and imported names (``from m import f``, ``import m`` +
        ``m.f``). Returns None for anything it cannot prove — project rules
        must treat unresolved calls as opaque, never guessed.
        """
        if raw.startswith(("self.", "cls.")) and caller is not None and caller.cls is not None:
            rest = raw.split(".", 1)[1]
            cls = summary.classes.get(caller.cls)
            if cls is None:
                return None
            if "." not in rest:
                return self._method(cls, rest)
            attr, _, meth = rest.partition(".")
            if "." in meth:
                return None
            for candidate in self.class_mro(cls):
                attr_type = candidate.attr_types.get(attr)
                if attr_type is None:
                    continue
                target_cls = self.resolve_class(attr_type, self.modules.get(candidate.module, summary))
                if target_cls is not None:
                    return self._method(target_cls, meth)
            return None
        head, _, rest = raw.partition(".")
        # local variable / parameter with an inferable class type
        if caller is not None and head in caller.local_types and rest and "." not in rest:
            cls_facts = self.resolve_class(caller.local_types[head], summary)
            if cls_facts is not None:
                return self._method(cls_facts, rest)
        # same-module lookups
        if raw in summary.functions:
            return summary.functions[raw]
        if raw in summary.classes:
            return self._method(summary.classes[raw], "__init__")
        if rest and head in summary.classes and "." not in rest:
            return self._method(summary.classes[head], rest)
        # imported names
        fq = self._resolve_alias(raw, summary)
        if fq is not None:
            return self._lookup_fq(fq)
        return None

    # -- locks ---------------------------------------------------------------

    def lock_node(self, token: str, summary: ModuleSummary, facts: FunctionFacts) -> Optional[str]:
        """Global lock id for a raw token: instance locks are named by their
        DECLARING class (``module.Class._lock``, subclasses share the node),
        module locks by ``module.name``."""
        if token.startswith(_MOD_LOCK_PREFIX):
            return f"{summary.module}.{token[len(_MOD_LOCK_PREFIX):]}"
        attr = token.split(".", 1)[1]
        if facts.cls is None:
            return None
        cls = summary.classes.get(facts.cls)
        if cls is None:
            return None
        for candidate in self.class_mro(cls):
            if attr in candidate.lock_attrs:
                return f"{candidate.module}.{candidate.name}.{attr}"
        return f"{cls.module}.{cls.name}.{attr}"

    def transitive_acquisitions(self, facts: FunctionFacts) -> "Dict[str, Tuple[Tuple[str, ...], int]]":
        """All lock nodes ``facts`` may acquire, directly or through resolved
        calls: ``{lock_node: (call chain of "module:qualname" ids, line)}``.
        Memoized; call-graph cycles terminate via the in-progress marker."""
        memo = self._acq_memo
        if facts.fq in memo:
            return memo[facts.fq]
        memo[facts.fq] = {}  # in-progress marker breaks recursion
        out: Dict[str, Tuple[Tuple[str, ...], int]] = {}
        summary = self.modules.get(facts.module)
        if summary is None:
            return out
        for token, line, _held in facts.acquisitions:
            node = self.lock_node(token, summary, facts)
            if node is not None:
                out.setdefault(node, ((facts.fq,), line))
        # a *_locked method's contract is "caller holds the lock": any caller
        # must acquire its class's lock around the call, so the convention
        # lock counts as an acquisition for lock-ORDER purposes
        if facts.cls is not None and facts.name.endswith("_locked"):
            cls = summary.classes.get(facts.cls)
            primary = cls.primary_lock() if cls is not None else None
            if primary is not None:
                node = self.lock_node(f"self.{primary}", summary, facts)
                if node is not None:
                    out.setdefault(node, ((facts.fq,), facts.line))
        for call in facts.calls:
            callee = self.resolve_call(call.raw, summary, facts)
            if callee is None or callee.fq == facts.fq:
                continue
            for node, (chain, line) in self.transitive_acquisitions(callee).items():
                out.setdefault(node, ((facts.fq,) + chain, line))
        memo[facts.fq] = out
        return out

    # -- contextvars ---------------------------------------------------------

    def contextvar_reads(self, facts: FunctionFacts) -> "List[Tuple[str, int]]":
        """Resolved ContextVar reads in ``facts``: ``[(fq var name, line)]``."""
        summary = self.modules.get(facts.module)
        if summary is None:
            return []
        out: List[Tuple[str, int]] = []
        for recv, line in facts.cv_reads:
            if "." not in recv and recv in summary.contextvars:
                out.append((f"{summary.module}.{recv}", line))
                continue
            fq = self._resolve_alias(recv, summary)
            if fq is None:
                continue
            mod, _, sym = fq.rpartition(".")
            target = self.modules.get(mod)
            if target is not None and sym in target.contextvars:
                out.append((f"{mod}.{sym}", line))
        return out

    def transitive_contextvar_reads(
        self, facts: FunctionFacts
    ) -> "Dict[str, Tuple[Tuple[str, ...], int]]":
        """ContextVars read by ``facts`` or anything it (resolvably) calls:
        ``{fq var: (call chain, line)}``. BFS with a visited set."""
        out: Dict[str, Tuple[Tuple[str, ...], int]] = {}
        queue: List[Tuple[FunctionFacts, Tuple[str, ...]]] = [(facts, (facts.fq,))]
        seen = {facts.fq}
        while queue:
            current, chain = queue.pop(0)
            for var, line in self.contextvar_reads(current):
                out.setdefault(var, (chain, line))
            summary = self.modules.get(current.module)
            if summary is None:
                continue
            for call in current.calls:
                callee = self.resolve_call(call.raw, summary, current)
                if callee is not None and callee.fq not in seen:
                    seen.add(callee.fq)
                    queue.append((callee, chain + (callee.fq,)))
        return out

    # -- jit reachability ----------------------------------------------------

    def jit_entry_functions(self) -> "List[FunctionFacts]":
        return [facts for facts in self.iter_functions() if facts.jit_entry]

    def reachable_from(self, entries: "Sequence[FunctionFacts]") -> "List[FunctionFacts]":
        """Cross-module call-graph closure from ``entries`` (the index-backed
        upgrade of TPU001's intra-module BFS)."""
        seen: Dict[str, FunctionFacts] = {}
        queue = list(entries)
        while queue:
            facts = queue.pop()
            if facts.fq in seen:
                continue
            seen[facts.fq] = facts
            summary = self.modules.get(facts.module)
            if summary is None:
                continue
            for call in facts.calls:
                callee = self.resolve_call(call.raw, summary, facts)
                if callee is not None and callee.fq not in seen:
                    queue.append(callee)
        return list(seen.values())


def function_cfg(summary: ModuleSummary, facts: FunctionFacts):
    """The control-flow graph for ``facts``, memoized on its module summary.

    Summaries are content-hash cached (:data:`_CACHE`), so this inherits the
    same invalidation: a warm ``run_lint`` reuses every CFG of every unchanged
    file, and an edited file drops its summary — and with it its CFGs —
    atomically.  Keyed by ``(qualname, line)`` so nested/shadowed defs cannot
    collide.
    """
    from unionml_tpu.analysis.cfg import build_cfg

    key = (facts.qualname, facts.line)
    cfg = summary.cfgs.get(key)
    if cfg is None:
        cfg = build_cfg(facts.node)
        summary.cfgs[key] = cfg
    return cfg


# --------------------------------------------------------------------- cache

#: path -> (sha256 of file bytes, summary). Process-global: a warm run_lint
#: re-summarizes only files whose content changed.
_CACHE: Dict[str, Tuple[str, ModuleSummary]] = {}


def clear_index_cache() -> None:
    """Drop all cached summaries (benchmarks use this for cold-run timing)."""
    _CACHE.clear()


def build_index(
    files: "Sequence[Path]",
) -> "Tuple[ProjectIndex, List[Tuple[str, str]], Dict[str, int]]":
    """Build (or incrementally refresh) the project index over ``files``.

    Returns ``(index, parse_errors, stats)`` where ``stats`` counts cache
    ``hits``/``misses`` — the incremental contract the tier-1 perf gate and
    the benchmark lane both ride on.
    """
    summaries: List[ModuleSummary] = []
    errors: List[Tuple[str, str]] = []
    hits = 0
    misses = 0
    for path in files:
        key = str(path)
        try:
            data = path.read_bytes()
        except OSError as exc:
            errors.append((key, str(exc)))
            continue
        digest = hashlib.sha256(data).hexdigest()
        cached = _CACHE.get(key)
        if cached is not None and cached[0] == digest:
            summaries.append(cached[1])
            hits += 1
            continue
        misses += 1
        try:
            source = data.decode("utf-8")
            tree = ast.parse(source, filename=key)
        except (SyntaxError, ValueError, UnicodeDecodeError) as exc:
            errors.append((key, str(exc)))
            _CACHE.pop(key, None)
            continue
        summary = build_summary(path, source, tree)
        _CACHE[key] = (digest, summary)
        summaries.append(summary)
    return ProjectIndex(summaries), errors, {"hits": hits, "misses": misses}
