"""tpu-lint: a TPU/concurrency-aware static analyzer for this codebase.

Five AST rules target the hazard classes the serving/training stack actually
has (host syncs under jit, use-after-donate, unlocked cross-thread mutation,
blocking calls in engine loops, bare env-var numeric parses); the engine walks
files, applies per-line ``# tpu-lint: disable=RULE`` suppressions, and renders
text or JSON. Run it as ``unionml-tpu lint [paths]`` or
``python -m unionml_tpu.analysis``; the tier-1 gate
(tests/unit/test_syntax.py) asserts ``run_lint(["unionml_tpu"])`` stays clean.
See docs/static-analysis.md for the rule catalog.
"""

from __future__ import annotations

from unionml_tpu.analysis.engine import (
    Finding,
    LintResult,
    Rule,
    all_rules,
    main,
    render_json,
    render_text,
    run_lint,
)

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "all_rules",
    "main",
    "render_json",
    "render_text",
    "run_lint",
]
