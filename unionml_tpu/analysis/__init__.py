"""tpu-lint: a TPU/concurrency-aware static analyzer for this codebase.

Twelve rules target the hazard classes the serving/training stack actually
has. Nine are per-file AST rules (host syncs under jit, use-after-donate,
unlocked cross-thread mutation, blocking calls in engine loops, bare env-var
numeric parses, wall-clock durations, unlocked ``*_locked`` calls, leaked
engine threads, unbounded per-key registries); three are whole-program rules
over a cross-module project index (lock-order cycles, recompile hazards at
jit static positions, contextvar reads behind executor/thread hops), and
TPU001/TPU002 use the same index to follow jit reachability and donation
across module boundaries. The engine walks files, applies per-line
``# tpu-lint: disable=RULE`` suppressions, and renders text, JSON, or SARIF
2.1.0. Run it as ``unionml-tpu lint [paths]`` or
``python -m unionml_tpu.analysis``; the tier-1 gate
(tests/unit/test_syntax.py) asserts ``run_lint(["unionml_tpu"])`` stays clean.
See docs/static-analysis.md for the rule catalog and the whole-program
architecture notes.
"""

from __future__ import annotations

from unionml_tpu.analysis.engine import (
    Finding,
    LintResult,
    Rule,
    all_rules,
    main,
    render_json,
    render_sarif,
    render_text,
    run_lint,
)
from unionml_tpu.analysis.project import ProjectIndex, build_index, clear_index_cache

__all__ = [
    "Finding",
    "LintResult",
    "ProjectIndex",
    "Rule",
    "all_rules",
    "build_index",
    "clear_index_cache",
    "main",
    "render_json",
    "render_sarif",
    "render_text",
    "run_lint",
]
