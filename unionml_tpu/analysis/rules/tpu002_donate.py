"""TPU002 — use of a buffer after passing it at a donated position.

``jax.jit(fn, donate_argnums=...)`` hands the argument's device buffer to XLA
for in-place reuse: after the call the Python object still exists but its
buffer is deleted, and touching it raises (or, through stale references on
some backends, silently reads garbage). The correct idiom rebinds the name
from the call's result — ``state = step(state, batch)`` — which this rule
recognizes as safe. Only literal ``donate_argnums`` are analyzed: a variable
value (e.g. gated on ``debug_disable_donation``) cannot be resolved
statically and is never guessed.

Scope: same-file dataflow, plus an index-backed cross-module pass
(:meth:`UseAfterDonate.check_project`): a donor defined in one module
(``@partial(jax.jit, donate_argnums=...)`` or a module-level
``step = jax.jit(fn, donate_argnums=...)`` binding) and imported into
another is invisible to the per-file pass — the project index's donor table
makes the importing module's call sites subject to the same later-load
analysis. Donating callables are collected from local
``f = jax.jit(g, donate_argnums=...)`` bindings, class-wide
``self._f = jax.jit(...)`` attributes, ``@partial(jax.jit, donate_argnums=...)``
decorators, and immediate ``jax.jit(g, ...)(args)`` invocations.

Path sensitivity: donations are solved as a reaching-definitions problem over
the function's CFG (:mod:`unionml_tpu.analysis.cfg`) rather than by source
line order.  A load in the *other* branch of the donating ``if`` is clean; a
load lexically above the donation but reachable again through a loop back
edge is flagged; a rebind on one path does not launder the other path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import (
    assign_target_names,
    call_target,
    dotted,
    is_jit_decorator,
    iter_scope,
    jit_wrap_call,
    literal_argnums,
)


def _donated_positions(call: ast.Call) -> "Optional[Tuple[int, ...]]":
    for keyword in call.keywords:
        if keyword.arg == "donate_argnums":
            return literal_argnums(keyword.value)
    return None


def _make_donation_flow():
    """Reaching-donations dataflow: fact ``(name, donate_line)``, generated at
    the donating call's statement, killed by any Store/Del of the name (the
    rebind idiom).  Built lazily so the per-file fast path doesn't import the
    dataflow machinery until a donation is actually seen."""
    from unionml_tpu.analysis.dataflow import Problem

    class _DonationFlow(Problem):
        def __init__(self, gens):
            self._gens = gens

        def gen_kill(self, node):
            gen = self._gens.get(node.nid, set())
            kill = set()
            for expr in node.exprs:
                if expr is None:
                    continue
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, (ast.Store, ast.Del)
                    ):
                        kill.add(sub.id)
            return gen, kill

        def apply_kill(self, facts, kill):
            return {f for f in facts if f[0] not in kill}

    return _DonationFlow


class UseAfterDonate(Rule):
    id = "TPU002"
    title = "buffer used after being passed at a donated position"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        attr_donors = self._class_attribute_donors(tree)
        module_donors = self._decorated_donors(tree)
        # module level counts as a scope too (module-scope jit wrap + call)
        scopes: "List[ast.AST]" = [tree]
        scopes += [n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            findings.extend(self._check_scope(scope, path, attr_donors, module_donors))
        return findings

    def check_project(self, index) -> "List[Finding]":
        """Cross-module donors: a module that imports a donating callable gets
        the same later-load analysis, with the import alias as the donor name."""
        findings: "List[Finding]" = []
        for summary in index.modules.values():
            imported: "Dict[str, Tuple[int, ...]]" = {}
            for alias, fq in summary.imports.items():
                mod, _, sym = fq.rpartition(".")
                donor_module = index.modules.get(mod)
                if donor_module is not None and sym in donor_module.donors:
                    imported[alias] = donor_module.donors[sym]
            if not imported:
                continue
            tree = summary.tree
            scopes: "List[ast.AST]" = [tree]
            scopes += [
                n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for scope in scopes:
                findings.extend(self._check_scope(scope, summary.path, {}, imported))
        return findings

    # ------------------------------------------------------------ donor discovery

    @staticmethod
    def _class_attribute_donors(tree: ast.Module) -> "Dict[str, Tuple[int, ...]]":
        """``self._f = jax.jit(..., donate_argnums=<literal>)`` anywhere in a
        class -> ``{"self._f": positions}`` (methods of the same class call
        through the attribute)."""
        donors: "Dict[str, Tuple[int, ...]]" = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = dotted(node.targets[0])
            wrap = jit_wrap_call(node.value)
            if target is None or wrap is None or not target.startswith(("self.", "cls.")):
                continue
            positions = _donated_positions(wrap)
            if positions:
                donors["self." + target.split(".", 1)[1]] = positions
        return donors

    @staticmethod
    def _decorated_donors(tree: ast.Module) -> "Dict[str, Tuple[int, ...]]":
        """``@partial(jax.jit, donate_argnums=<literal>)`` functions, callable
        by bare name within the module."""
        donors: "Dict[str, Tuple[int, ...]]" = {}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and is_jit_decorator(dec):
                    positions = _donated_positions(dec)
                    if positions:
                        donors[node.name] = positions
        return donors

    # ------------------------------------------------------------ per-scope check

    def _check_scope(
        self,
        scope: ast.AST,
        path: str,
        attr_donors: "Dict[str, Tuple[int, ...]]",
        module_donors: "Dict[str, Tuple[int, ...]]",
    ) -> "List[Finding]":
        donors = dict(module_donors)
        donors.update(attr_donors)
        statements = list(iter_scope(scope))
        # pass 1: local `f = jax.jit(g, donate_argnums=...)` bindings
        for node in statements:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = dotted(node.targets[0])
                wrap = jit_wrap_call(node.value)
                if target and wrap:
                    positions = _donated_positions(wrap)
                    if positions:
                        donors[target] = positions

        # pass 2: call sites -> donated argument names, keyed by the Call node
        # so the CFG pass below can attach each donation to its statement
        donated_by_call: "Dict[int, List[str]]" = {}
        for call in statements:
            if not isinstance(call, ast.Call):
                continue
            positions = self._call_donated_positions(call, donors)
            if positions is None:
                continue
            if any(isinstance(arg, ast.Starred) for arg in call.args):
                continue  # positions unknowable through *args
            rebound = self._rebound_names(statements, call)
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if isinstance(arg, ast.Name) and arg.id not in rebound:
                    donated_by_call.setdefault(id(call), []).append(arg.id)
        if not donated_by_call:
            return []

        # pass 3 (path-sensitive): solve reaching-donations over the CFG and
        # flag only loads a donation actually reaches with no intervening
        # rebind.  A load in the *other* branch of the donating `if` is clean;
        # a load lexically before the donation but reached again through a
        # loop back edge is not.
        from unionml_tpu.analysis.cfg import build_cfg
        from unionml_tpu.analysis.dataflow import solve_forward

        cfg = build_cfg(scope)
        gens: "Dict[int, Set[Tuple[str, int]]]" = {}
        for node in cfg.statement_nodes():
            for expr in node.exprs:
                if expr is None:
                    continue
                for sub in ast.walk(expr):
                    for name in donated_by_call.get(id(sub), ()):
                        gens.setdefault(node.nid, set()).add((name, sub.lineno))
        sol = solve_forward(cfg, _make_donation_flow()(gens))

        findings: "List[Finding]" = []
        flagged: "Set[Tuple[str, int]]" = set()
        for node in cfg.statement_nodes():
            live = sol.in_facts(node.nid)
            if not live:
                continue
            live_names = {name: donated_at for name, donated_at in sorted(live)}
            for expr in node.exprs:
                if expr is None:
                    continue
                for sub in ast.walk(expr):
                    if (
                        isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in live_names
                        and (sub.id, sub.lineno) not in flagged
                    ):
                        flagged.add((sub.id, sub.lineno))
                        donated_at = live_names[sub.id]
                        findings.append(
                            self.finding(
                                path, sub,
                                f"'{sub.id}' was donated to a jit-compiled call on line {donated_at} "
                                "(donate_argnums) — its buffer is deleted after the call; rebind the "
                                "name from the call's result instead",
                            )
                        )
        return findings

    @staticmethod
    def _call_donated_positions(call: ast.Call, donors: "Dict[str, Tuple[int, ...]]"):
        target = call_target(call)
        if target is not None:
            if target.startswith(("self.", "cls.")):
                target = "self." + target.split(".", 1)[1]
            if target in donors:
                return donors[target]
        # immediate invocation: jax.jit(g, donate_argnums=...)(args)
        wrap = jit_wrap_call(call.func)
        if wrap is not None:
            return _donated_positions(wrap)
        return None

    @staticmethod
    def _rebound_names(statements, call: ast.Call) -> "Set[str]":
        """Names the call's own result assignment rebinds (``a, b = f(a, x)``
        consumes and replaces ``a`` — the donation-safe idiom)."""
        for node in statements:
            if isinstance(node, ast.Assign) and node.value is call:
                out: "Set[str]" = set()
                for target in node.targets:
                    out.update(assign_target_names(target))
                return out
            if isinstance(node, ast.AugAssign) and node.value is call:
                name = dotted(node.target)
                return {name} if name else set()
        return set()
