"""TPU012 — executor/thread target reads a ContextVar without a ctx.run wrap.

The serving stack carries per-request identity in ``contextvars``: the
request id and trace (observability/trace.py), the tenant and priority tier
(serving/tenancy.py), the request deadline (serving/overload.py), the query
params (serving/http.py). ``loop.run_in_executor`` and ``threading.Thread``
do NOT propagate the submitting context — the target runs in the worker's
empty context, every ``.get()`` silently returns its default, and the symptom
is subtle: a stream billed to no tenant, a trace that loses its request id
the moment work hops threads. PR 5 fixed several of these holes by hand with
the canonical wrap::

    ctx = contextvars.copy_context()
    await loop.run_in_executor(None, ctx.run, next, iterator, sentinel)

but nothing kept new call sites honest — the read is usually two or three
helper calls below the submitted target, in another module, invisible to any
per-file rule. This rule closes the class: for every
``run_in_executor``/``submit``/``Thread(target=...)`` submission in the
index, it resolves the target through the cross-module call graph and flags
it when anything reachable reads a ContextVar, unless the submission is
already wrapped (``ctx.run`` as the submitted callable, or
``partial(ctx.run, fn)``). Targets the index cannot resolve (stored
callables, dynamic dispatch) are never guessed at; lambdas are followed into
their call targets.
"""

from __future__ import annotations

import ast
from typing import List

from unionml_tpu.analysis.engine import Finding, Rule


class ContextvarExecutorHole(Rule):
    id = "TPU012"
    title = "executor/thread target reads a ContextVar without ctx.run"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # the read is typically modules away from the submission; index-only

    def check_project(self, index) -> "List[Finding]":
        findings: "List[Finding]" = []
        for facts in sorted(index.iter_functions(), key=lambda f: (f.path, f.line, f.qualname)):
            summary = index.modules.get(facts.module)
            if summary is None:
                continue
            for sub in facts.executor_calls:
                if sub.wrapped:
                    continue
                targets = []
                if sub.target_raw is not None:
                    targets.append(sub.target_raw)
                targets.extend(sub.lambda_calls)
                hit = None
                for raw in targets:
                    callee = index.resolve_call(raw, summary, facts)
                    if callee is None:
                        continue
                    reads = index.transitive_contextvar_reads(callee)
                    if reads:
                        var = sorted(reads)[0]
                        chain, line = reads[var]
                        hit = (raw, var, chain, line)
                        break
                if hit is None:
                    continue
                raw, var, chain, line = hit
                via = " -> ".join(chain)
                kind = "Thread target" if sub.kind == "thread" else "executor target"
                findings.append(
                    Finding(
                        rule=self.id,
                        path=facts.path,
                        line=sub.line,
                        col=0,
                        message=(
                            f"{kind} '{raw}' reads ContextVar '{var}' (via {via}, line {line}) "
                            "but executors/threads do not inherit the submitting context — the "
                            "read silently returns the default; wrap the callable: "
                            "ctx = contextvars.copy_context(); submit ctx.run(...) instead"
                        ),
                    )
                )
        return findings
