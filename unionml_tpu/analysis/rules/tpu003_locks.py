"""TPU003 — unlocked mutation of lock-guarded shared state.

The serving stack is threaded (submit from executor threads, a dedicated
engine thread, replica schedulers), and its classes follow one discipline: a
class that owns a ``threading.Lock``/``RLock``/``Condition`` guards its shared
attributes with ``with self._lock:`` blocks. The race this rule catches is the
half-guarded attribute: ``self._x`` is mutated or read under the lock in one
method and mutated WITHOUT it in another — two threads interleave, an
increment is lost or a list is resized mid-iteration, and it only reproduces
under production load.

Conventions honored (both are this codebase's existing idiom):

- ``__init__``/``__new__``/``__del__`` are exempt — construction happens
  before the object is shared;
- methods named ``*_locked`` are exempt — their docstring contract is
  "caller holds the lock" and the engine calls them from inside ``with``
  blocks (flagging them would punish the helper-extraction the discipline
  encourages).

Unlocked READS are deliberately not flagged: snapshot-style reads of counters
are a documented pattern here (and mostly benign); lost-update mutations are
the class of bug that corrupts state.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import List, Optional, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import LOCK_FACTORIES, call_target, self_attribute

_LOCK_FACTORIES = LOCK_FACTORIES

#: method calls that mutate their receiver in place
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "popitem", "clear", "update", "add", "discard", "setdefault", "sort", "reverse",
}

_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}


@dataclasses.dataclass
class _Access:
    attr: str
    mutation: bool
    under_lock: bool
    node: ast.AST
    method: str


class UnlockedSharedMutation(Rule):
    id = "TPU003"
    title = "lock-guarded attribute mutated outside the lock"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, cls: ast.ClassDef, path: str) -> "List[Finding]":
        locks = self._lock_attributes(cls)
        if not locks:
            return []
        accesses: "List[_Access]" = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS or method.name.endswith("_locked"):
                continue
            self._walk(method, method.name, locks, under_lock=False, accesses=accesses)
        guarded: "Set[str]" = {a.attr for a in accesses if a.under_lock}
        findings: "List[Finding]" = []
        for access in accesses:
            if access.mutation and not access.under_lock and access.attr in guarded:
                findings.append(
                    self.finding(
                        path, access.node,
                        f"'self.{access.attr}' is mutated in {access.method}() without holding "
                        f"the lock, but is accessed under 'with self.{sorted(locks)[0]}:' "
                        "elsewhere in the class — racy lost update",
                    )
                )
        return findings

    # ------------------------------------------------------------------ helpers

    @staticmethod
    def _lock_attributes(cls: ast.ClassDef) -> "Set[str]":
        """Attributes assigned a Lock/RLock/Condition anywhere in the class."""
        locks: "Set[str]" = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_target(node.value) in _LOCK_FACTORIES:
                    for target in node.targets:
                        attr = self_attribute(target)
                        if attr is not None and isinstance(target, ast.Attribute):
                            locks.add(attr)
        return locks

    def _walk(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        accesses: "List[_Access]",
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                continue  # nested scopes: a closure's lock discipline is its own
            if isinstance(child, ast.With):
                holds = under_lock or any(
                    self_attribute(item.context_expr) in locks for item in child.items
                )
                for item in child.items:
                    self._record_expr(item.context_expr, method, locks, under_lock, accesses)
                for stmt in child.body:
                    self._walk(stmt, method, locks, holds, accesses)
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign)):
                targets = child.targets if isinstance(child, ast.Assign) else [child.target]
                for target in targets:
                    self._record_target(target, method, locks, under_lock, accesses)
                if child.value is not None:
                    self._record_expr(child.value, method, locks, under_lock, accesses)
                continue
            if isinstance(child, ast.AugAssign):
                self._record_target(child.target, method, locks, under_lock, accesses, aug=True)
                self._record_expr(child.value, method, locks, under_lock, accesses)
                continue
            self._record_expr(child, method, locks, under_lock, accesses, walk_children=False)
            self._walk(child, method, locks, under_lock, accesses)

    def _record_target(
        self,
        target: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        accesses: "List[_Access]",
        aug: bool = False,
    ) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for element in target.elts:
                self._record_target(element, method, locks, under_lock, accesses, aug=aug)
            return
        if isinstance(target, ast.Starred):
            self._record_target(target.value, method, locks, under_lock, accesses, aug=aug)
            return
        # self.x = ..., self.x[k] = ..., self.x.y = ... all mutate self.x
        attr = self_attribute(target)
        if attr is not None and attr not in locks:
            accesses.append(_Access(attr, True, under_lock, target, method))

    def _record_expr(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        accesses: "List[_Access]",
        walk_children: bool = True,
    ) -> None:
        """Record reads of self attributes and in-place-mutating method calls."""
        nodes = ast.walk(node) if walk_children else [node]
        for child in nodes:
            if isinstance(child, ast.Call) and isinstance(child.func, ast.Attribute):
                if child.func.attr in _MUTATING_METHODS:
                    attr = self_attribute(child.func.value)
                    if attr is not None and attr not in locks:
                        accesses.append(_Access(attr, True, under_lock, child, method))
            elif isinstance(child, ast.Attribute) and isinstance(child.ctx, ast.Load):
                attr = self_attribute(child)
                if attr is not None and attr not in locks:
                    accesses.append(_Access(attr, False, under_lock, child, method))
