"""TPU019 — release skipped on a non-exception early exit.

The CFG twin of TPU016: same acquire/release protocol table, same dataflow
facts, but the sink is an explicit ``return`` instead of the RAISE exit.  The
shape it catches is a guard clause added after the acquire::

    conn = HTTPConnection(host)
    if self._draining:
        return None          # <- conn leaks on this path
    ...
    conn.close()

The rule only fires when the function *does* release the protocol somewhere
— a function whose whole job is to acquire and hand the resource off
(``return conn``, ``self._conn = conn``) transfers ownership, which the
escape semantics already recognize; and a function with no release at all is
TPU016's business on its exception paths, not a half-finished release
discipline.  Requiring an in-function release keeps this rule's findings
"you released on the other paths, you forgot this one" — always actionable.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import dotted, iter_scope
from unionml_tpu.analysis.rules._flow import (
    CLOSE_PROTOS,
    PROTOCOLS,
    ResourceFlow,
    _loaded_names,
    derived_acquirers,
    function_hints,
    solve_resources,
)
from unionml_tpu.analysis.rules.tpu016_resource_leak import _make_resolver, _relevant


def released_protos(func: ast.AST) -> "Set[str]":
    """Protocols this function explicitly releases somewhere in its body."""
    out: "Set[str]" = set()
    for node in iter_scope(func):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        method = node.func.attr
        if method == "close":
            out |= CLOSE_PROTOS
        elif method == "release":
            out.add("radix-pin")
        elif method in ("extend", "append") and "free_blocks" in (
            dotted(node.func.value) or ""
        ):
            out.add("kv-blocks")
    return out


class UnreleasedOnEarlyReturn(Rule):
    id = "TPU019"
    title = "early return skips a release other paths perform"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # flow analysis runs in the project pass (CFGs are cached there)

    def check_project(self, index) -> "List[Finding]":
        from unionml_tpu.analysis.project import function_cfg

        derived = derived_acquirers(index)
        derived_names = {fq.rsplit(".", 1)[-1] for fq in derived}
        findings: "List[Finding]" = []
        for summary in sorted(index.modules.values(), key=lambda s: s.path):
            for facts in sorted(
                summary.functions.values(), key=lambda f: (f.line, f.qualname)
            ):
                hints = function_hints(summary, facts)
                if not _relevant(hints, derived_names):
                    continue
                released = released_protos(facts.node)
                if not released:
                    continue
                resolve = _make_resolver(index, summary, facts, derived, derived_names)
                cfg = function_cfg(summary, facts)
                sol = solve_resources(cfg, ResourceFlow(resolve))
                # a fact live AT a `return` can still die on the way out — a
                # `finally` between the return and the function exit releases
                # on every path — so only facts that also survive to EXIT leak
                escaped = sol.at_exit
                for node in cfg.statement_nodes():
                    if not isinstance(node.stmt, ast.Return) or not sol.reachable(node.nid):
                        continue
                    returned = (
                        _loaded_names(node.stmt.value) if node.stmt.value is not None else set()
                    )
                    for var, proto_name, line in sorted(sol.in_facts(node.nid)):
                        if (var, proto_name, line) not in escaped:
                            continue
                        if proto_name not in released or var in returned:
                            continue
                        proto = PROTOCOLS[proto_name]
                        findings.append(
                            Finding(
                                rule=self.id,
                                path=facts.path,
                                line=node.line,
                                col=0,
                                message=(
                                    f"returning here leaves '{var}' ({proto.noun}, acquired "
                                    f"line {line}) unreleased, while other paths in this "
                                    f"function release it — release before this return, or "
                                    f"restructure so the release is unconditional "
                                    f"(try/finally or with)"
                                ),
                            )
                        )
        return findings
