"""tpu-lint rule registry.

Each rule lives in its own module; adding a rule is: write the module, import
its class here, add it to :data:`RULES`. The engine instantiates from this
mapping (:func:`unionml_tpu.analysis.engine.all_rules`), so the registry is
the single source of truth for ``--select``/``--ignore`` validation and the
docs rule catalog.
"""

from __future__ import annotations

from unionml_tpu.analysis.rules.tpu001_host_sync import HostSyncInJit
from unionml_tpu.analysis.rules.tpu002_donate import UseAfterDonate
from unionml_tpu.analysis.rules.tpu003_locks import UnlockedSharedMutation
from unionml_tpu.analysis.rules.tpu004_blocking import BlockingCallInServingLoop
from unionml_tpu.analysis.rules.tpu005_env import BareEnvNumericParse
from unionml_tpu.analysis.rules.tpu006_wall_clock import WallClockDuration
from unionml_tpu.analysis.rules.tpu007_locked_callers import UnlockedLockedHelperCall
from unionml_tpu.analysis.rules.tpu008_thread_leak import LeakedEngineThread
from unionml_tpu.analysis.rules.tpu009_registry import UnboundedPerKeyRegistry
from unionml_tpu.analysis.rules.tpu010_lock_order import LockOrderCycle
from unionml_tpu.analysis.rules.tpu011_recompile import RecompileHazard
from unionml_tpu.analysis.rules.tpu012_contextvar import ContextvarExecutorHole
from unionml_tpu.analysis.rules.tpu013_locked_collectives import BlockingCollectiveUnderLock
from unionml_tpu.analysis.rules.tpu014_unseeded_random import UnseededRandomness
from unionml_tpu.analysis.rules.tpu015_unbounded_retry import UnboundedNetworkRetry
from unionml_tpu.analysis.rules.tpu016_resource_leak import ResourceLeakOnException
from unionml_tpu.analysis.rules.tpu017_charge_refund import ChargeWithoutRefund
from unionml_tpu.analysis.rules.tpu018_lock_yield import LockHeldAcrossYield
from unionml_tpu.analysis.rules.tpu019_early_return import UnreleasedOnEarlyReturn

__all__ = ["RULES"]

RULES = {
    cls.id: cls
    for cls in (
        HostSyncInJit,
        UseAfterDonate,
        UnlockedSharedMutation,
        BlockingCallInServingLoop,
        BareEnvNumericParse,
        WallClockDuration,
        UnlockedLockedHelperCall,
        LeakedEngineThread,
        UnboundedPerKeyRegistry,
        LockOrderCycle,
        RecompileHazard,
        ContextvarExecutorHole,
        BlockingCollectiveUnderLock,
        UnseededRandomness,
        UnboundedNetworkRetry,
        ResourceLeakOnException,
        ChargeWithoutRefund,
        LockHeldAcrossYield,
        UnreleasedOnEarlyReturn,
    )
}
