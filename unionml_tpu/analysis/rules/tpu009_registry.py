"""TPU009 — unbounded per-key registry: request-derived keys, no eviction.

A serving process lives for weeks; its host memory must be bounded by
CONSTRUCTION, not by hoping traffic is polite. The recurring bug shape: a
class keeps a ``dict`` keyed by a value the REQUEST chose — a tenant id, a
request id, a session/prefix key — and inserts on every request but never
evicts. A scanner (or one hostile tenant minting fresh ids) then grows the
map without bound: the multi-tenant registry, the flight recorder's in-flight
table, and the scheduler's affinity map are all exactly one missing eviction
away from this. The fixed forms in-tree: a bounded LRU (``popitem`` past a
capacity), idle-age eviction (``pop`` on a sweep), per-request removal
(``pop``/``del`` on completion), or rebuilding the map filtered (the resize
idiom).

The rule: inside ANY class, a subscript assignment (or ``setdefault``) on a
``self.<attr>`` whose KEY expression names a request-derived value — an
identifier whose last component contains ``tenant``, ``request_id``, ``rid``,
``session_id``, ``api_key``, or is exactly ``key``/``request`` — is flagged
unless the class shows an eviction path for that attribute somewhere:

- ``self.<attr>.pop(...)`` / ``.popitem(...)`` / ``.clear()``,
- ``del self.<attr>[...]``,
- a ``len(self.<attr>)`` comparison (the bound-check-then-evict idiom),
- re-assigning ``self.<attr>`` outside ``__init__`` (the filtered-rebuild
  idiom, e.g. the scheduler's resize).

Out of scope (conservative posture): module-level dicts (no lifecycle object
to bound), keys that are server-chosen (slot indices, route names), and
containers inserted into via methods (``.append`` lists are TPU008's thread
territory; bounded deques carry their own maxlen).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from unionml_tpu.analysis.engine import Finding, Rule

#: substrings of an identifier's LAST component that mark it request-derived
_KEY_MARKERS = ("tenant", "request_id", "rid", "session_id", "api_key")
#: exact identifiers that are request-derived on their own
_KEY_EXACT = {"key", "request"}
#: methods whose call on the attr counts as an eviction path
_EVICT_METHODS = {"pop", "popitem", "clear"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (None otherwise)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _key_identifier(expr: ast.AST) -> Optional[str]:
    """The identifier a subscript KEY ultimately names: a bare name, the last
    attribute component (``session.tenant`` -> ``tenant``), or a call's
    receiver is NOT followed (``id(state)`` is server-derived)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _request_derived(expr: ast.AST) -> bool:
    name = _key_identifier(expr)
    if name is None:
        return False
    lowered = name.lower()
    if lowered in _KEY_EXACT:
        return True
    return any(marker in lowered for marker in _KEY_MARKERS)


class UnboundedPerKeyRegistry(Rule):
    id = "TPU009"
    title = "request-keyed dict in a class with no eviction/bound path"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, cls: ast.ClassDef, path: str) -> "List[Finding]":
        inserts: "Dict[str, ast.AST]" = {}  # attr -> first insert site
        evictable = self._evictable_attrs(cls)
        for node in ast.walk(cls):
            attr = self._insert_attr(node)
            if attr is not None:
                inserts.setdefault(attr, node)
        return [
            self.finding(
                path, node,
                f"self.{attr} is inserted into with a request-derived key but the "
                "class has no eviction path for it (no pop/popitem/clear/del, no "
                "len() bound check, no filtered rebuild) — a hostile client minting "
                "fresh ids grows it without bound; add a capacity/idle eviction "
                "(see serving/tenancy.py's TenantRegistry)",
            )
            for attr, node in inserts.items()
            if attr not in evictable
        ]

    @staticmethod
    def _insert_attr(node: ast.AST) -> Optional[str]:
        """The ``self.<attr>`` a request-keyed insert targets, if ``node`` is
        one: ``self.X[key] = v`` or ``self.X.setdefault(key, v)``."""
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _self_attr(target.value)
                    if attr is not None and _request_derived(target.slice):
                        return attr
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "setdefault"
            and node.args
        ):
            attr = _self_attr(node.func.value)
            if attr is not None and _request_derived(node.args[0]):
                return attr
        return None

    @staticmethod
    def _evictable_attrs(cls: ast.ClassDef) -> "Set[str]":
        """Attributes with ANY eviction/bound evidence in the class."""
        evictable: "Set[str]" = set()
        for node in ast.walk(cls):
            # self.X.pop(...) / .popitem() / .clear()
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EVICT_METHODS
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    evictable.add(attr)
            # del self.X[...]
            if isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        attr = _self_attr(target.value)
                        if attr is not None:
                            evictable.add(attr)
            # len(self.X) in a comparison: the bound-check-then-evict idiom
            if isinstance(node, ast.Compare):
                for expr in [node.left, *node.comparators]:
                    if (
                        isinstance(expr, ast.Call)
                        and isinstance(expr.func, ast.Name)
                        and expr.func.id == "len"
                        and expr.args
                    ):
                        attr = _self_attr(expr.args[0])
                        if attr is not None:
                            evictable.add(attr)
        # re-assignment outside __init__: the filtered-rebuild idiom
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            for node in ast.walk(method):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        attr = _self_attr(target)
                        if attr is not None:
                            evictable.add(attr)
        return evictable
