"""TPU004 — blocking call inside a serving handler or engine-loop method.

The serving stack's latency budget lives in two kinds of code: ``async def``
request handlers (one blocked coroutine stalls the whole event loop — every
concurrent request, not just the offender) and engine-loop methods (the
``*_loop`` threads that own device dispatch — a sleep or sync there stalls
every resident stream's time-to-next-token). A ``time.sleep``, sync
subprocess/HTTP call, or ``block_until_ready`` in either is a whole-service
stall, not a per-request cost.

Scope: functions defined with ``async def`` (anywhere), plus sync methods
whose names mark them as serving loops (``*_loop``) or handlers
(``handle*``/``on_*``). A deliberate throttle in a watcher loop belongs in a
plain helper thread — or carries a justified suppression.
"""

from __future__ import annotations

import ast
from typing import List

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import call_target, iter_scope

#: dotted call names that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() blocks the thread (asyncio.sleep / Condition.wait with timeout)",
    "urllib.request.urlopen": "sync HTTP inside a serving path blocks the loop",
    "socket.create_connection": "sync socket connect inside a serving path blocks the loop",
}

_BLOCKING_PREFIXES = {
    "subprocess.": "sync subprocess call inside a serving path blocks the loop",
    "requests.": "sync HTTP (requests) inside a serving path blocks the loop",
}


def _is_serving_scope(func) -> bool:
    if isinstance(func, ast.AsyncFunctionDef):
        return True
    name = func.name
    return name.endswith("_loop") or name.startswith("handle") or name.startswith("on_")


class BlockingCallInServingLoop(Rule):
    id = "TPU004"
    title = "blocking call inside a serving handler / engine loop"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for func in ast.walk(tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_serving_scope(func):
                continue
            where = "async handler" if isinstance(func, ast.AsyncFunctionDef) else "engine-loop method"
            for node in iter_scope(func):
                if not isinstance(node, ast.Call):
                    continue
                target = call_target(node)
                if target in _BLOCKING_CALLS:
                    findings.append(
                        self.finding(path, node, f"{_BLOCKING_CALLS[target]} — in {where} '{func.name}'")
                    )
                    continue
                if target is not None:
                    for prefix, message in _BLOCKING_PREFIXES.items():
                        if target.startswith(prefix):
                            findings.append(
                                self.finding(path, node, f"{message} — in {where} '{func.name}'")
                            )
                            break
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                ):
                    findings.append(
                        self.finding(
                            path, node,
                            f"block_until_ready() fences the device queue — in {where} "
                            f"'{func.name}'; fetch the result (np.asarray) outside the hot "
                            "section or let async dispatch overlap",
                        )
                    )
        return findings
