"""TPU001 — host sync inside a jit-compiled function.

A jitted function runs as one async XLA dispatch; anything that pulls a traced
value back to the host (``.item()``, ``float()``/``int()`` on a tracer,
``np.asarray``, ``jax.device_get``, ``.block_until_ready()`` or its
module-level twin ``jax.block_until_ready(x)``) either fails at
trace time or — worse, via implicit conversion paths — silently fences the
device queue, turning an overlap-everything pipeline into a round-trip per
step. ``print`` runs at trace time only (usually a debugging leftover; use
``jax.debug.print``). The rule marks every function that is jit-compiled
(``@jax.jit``/``@partial(jax.jit, ...)`` decorators, or ``jax.jit(fn)``
wrapping of a module function, method, or nested function), follows the
intra-module call graph from those entry points, and flags host-sync
operations anywhere in the reachable set.

Whole-program upgrade: :meth:`HostSyncInJit.check_project` re-runs the same
scan over the PROJECT index's cross-module reachable set — a jitted entry in
``serving/continuous.py`` calling a helper imported from ``ops/`` now carries
the taint into that helper's module, where the per-file pass could never
follow. Intra-module duplicates are dropped by the engine's dedupe.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import (
    call_target,
    dotted,
    is_jit_decorator,
    iter_scope,
    jit_wrap_call,
)

#: calls that are a host sync no matter what their argument is
_SYNC_CALLS = {
    "jax.device_get": "jax.device_get() pulls values to the host",
    # both spellings of the fence: x.block_until_ready() is _SYNC_METHODS
    "jax.block_until_ready": "jax.block_until_ready() fences the device queue",
    "np.asarray": "np.asarray() on a tracer forces a host transfer",
    "np.array": "np.array() on a tracer forces a host transfer",
    "numpy.asarray": "numpy.asarray() on a tracer forces a host transfer",
    "numpy.array": "numpy.array() on a tracer forces a host transfer",
}

#: zero-arg methods that sync when called on a device array
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

_FuncNode = ast.FunctionDef  # AsyncFunctionDef handled alongside


class HostSyncInJit(Rule):
    id = "TPU001"
    title = "host sync inside a jit-compiled function"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        functions, entries = self._collect(tree)
        reachable = self._reachable(functions, entries)
        findings: "List[Finding]" = []
        for func in reachable:
            findings.extend(self._scan(func, path))
        # jitted lambdas have no def to put in the graph: scan their body directly
        for node in ast.walk(tree):
            wrap = jit_wrap_call(node)
            if wrap is not None and wrap.args and isinstance(wrap.args[0], ast.Lambda):
                findings.extend(self._scan(wrap.args[0], path, params=self._params(wrap.args[0])))
        return findings

    def check_project(self, index) -> "List[Finding]":
        """Index-backed reachability: BFS from every jit entry point across
        the resolved cross-module call graph, scanning each reached function
        with the same host-sync detectors as the per-file pass."""
        findings: "List[Finding]" = []
        for facts in index.reachable_from(index.jit_entry_functions()):
            findings.extend(self._scan(facts.node, facts.path))
        return findings

    # ------------------------------------------------------------- collection

    @staticmethod
    def _params(func) -> "Set[str]":
        args = func.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return set(names)

    def _collect(self, tree: ast.Module):
        """All function defs keyed by the names a same-module call site would
        use (bare name for module/nested functions, ``self.name`` for methods),
        plus the jit entry-point set."""
        functions: "Dict[str, ast.AST]" = {}
        entries: "List[ast.AST]" = []

        def visit(node: ast.AST, in_class: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    functions[child.name] = child
                    if in_class:
                        functions[f"self.{child.name}"] = child
                    if any(is_jit_decorator(dec) for dec in child.decorator_list):
                        entries.append(child)
                    visit(child, in_class=False)
                elif isinstance(child, ast.ClassDef):
                    visit(child, in_class=True)
                else:
                    visit(child, in_class=in_class)

        visit(tree, in_class=False)

        # jax.jit(fn, ...) wrapping: the first positional argument names the
        # compiled function — module-level, local, or a self.method reference
        for node in ast.walk(tree):
            wrap = jit_wrap_call(node)
            if wrap is None or not wrap.args:
                continue
            target = dotted(wrap.args[0])
            if target is None:
                continue
            if target in functions:
                entries.append(functions[target])
            elif target.startswith(("self.", "cls.")):
                bare = target.split(".", 1)[1]
                if f"self.{bare}" in functions:
                    entries.append(functions[f"self.{bare}"])
        return functions, entries

    def _reachable(self, functions: "Dict[str, ast.AST]", entries: "List[ast.AST]"):
        """BFS over same-module call edges from the jit entry points."""
        queue = list(entries)
        seen: "List[ast.AST]" = []
        while queue:
            func = queue.pop()
            if any(func is s for s in seen):
                continue
            seen.append(func)
            for node in iter_scope(func):
                if not isinstance(node, ast.Call):
                    continue
                target = call_target(node)
                if target is None:
                    continue
                if target.startswith(("self.", "cls.")):
                    target = "self." + target.split(".", 1)[1]
                callee = functions.get(target)
                if callee is not None:
                    queue.append(callee)
        return seen

    # ------------------------------------------------------------- detection

    def _scan(self, func, path: str, params: "Optional[Set[str]]" = None) -> "List[Finding]":
        params = self._params(func) if params is None else params
        findings: "List[Finding]" = []
        body = func.body if isinstance(func.body, list) else [func.body]  # Lambda body is an expr
        for stmt in body:
            for node in [stmt, *iter_scope(stmt)]:
                if not isinstance(node, ast.Call):
                    continue
                target = call_target(node)
                if target == "print":
                    findings.append(
                        self.finding(
                            path, node,
                            "print() inside a jit-compiled function runs at trace time only "
                            "(use jax.debug.print for runtime values)",
                        )
                    )
                elif target in _SYNC_CALLS:
                    findings.append(
                        self.finding(path, node, f"{_SYNC_CALLS[target]} inside a jit-compiled function")
                    )
                elif target in ("float", "int") and len(node.args) == 1 and self._is_param_value(
                    node.args[0], params
                ):
                    findings.append(
                        self.finding(
                            path, node,
                            f"{target}() on a traced argument inside a jit-compiled function "
                            "forces a host sync (and fails under jit)",
                        )
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS
                    and not node.args
                ):
                    findings.append(
                        self.finding(
                            path, node,
                            f".{node.func.attr}() inside a jit-compiled function forces a host sync",
                        )
                    )
        return findings

    @staticmethod
    def _is_param_value(expr: ast.AST, params: "Set[str]") -> bool:
        """``int(x)`` / ``int(x[0])`` where ``x`` is a traced parameter. Shape
        and dtype accesses (``int(x.shape[0])``) are static under jit and stay
        allowed — only the bare value and element reads sync."""
        if isinstance(expr, ast.Name):
            return expr.id in params
        if isinstance(expr, ast.Subscript):
            return isinstance(expr.value, ast.Name) and expr.value.id in params
        return False
