"""TPU016 — resource leaks when an exception skips its release.

The serving stack is built out of paired acquire/release protocols: paged KV
blocks popped from a free list and extended back, radix prefix blocks pinned
and released, sockets/HTTP connections opened and closed, file handles.  The
happy path releases everything; the bug class that erodes a weeks-long
serving process is the *exception* path — a call that can raise between the
acquire and the release, outside any ``try/finally`` or ``with``, leaks the
resource forever (a leaked KV block shrinks batch capacity; a leaked pin
makes a prefix unevictable; a leaked connection pins a worker socket).

Mechanically: for every function that mentions a protocol acquire, solve the
:class:`~unionml_tpu.analysis.rules._flow.ResourceFlow` dataflow problem over
its CFG (exception edges included) and flag every acquisition fact that
reaches the synthetic RAISE exit — i.e. some path acquires, then propagates
an exception out of the function without releasing.  Ownership transfers
(returning the resource, storing it on an object, passing it to another
callable) kill the fact; ``with`` blocks and ``finally`` release on every
path by construction, so the only way to be flagged is a genuinely unguarded
window.

One-hop acquire wrappers are resolved through the project index: a call to a
function whose body is ``return HTTPConnection(...)`` acquires exactly what
the wrapped call does (``RemoteHost._connect`` is the in-tree case).
"""

from __future__ import annotations

import ast
from typing import List

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import call_target
from unionml_tpu.analysis.rules._flow import (
    PROTOCOLS,
    ResourceFlow,
    derived_acquirers,
    function_hints,
    solve_resources,
)


def _relevant(hints, derived_names) -> bool:
    if hints.protos or hints.has_pin:
        return True
    return any(raw.rsplit(".", 1)[-1] in derived_names for raw in hints.calls)


def _make_resolver(index, summary, facts, derived, derived_names):
    def resolve(call: ast.Call):
        target = call_target(call)
        if target is None or target.rsplit(".", 1)[-1] not in derived_names:
            return None
        callee = index.resolve_call(target, summary, facts)
        if callee is None:
            return None
        return derived.get(callee.fq)

    return resolve


class ResourceLeakOnException(Rule):
    id = "TPU016"
    title = "resource acquired but an exception path skips its release"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # flow analysis runs in the project pass (CFGs are cached there)

    def check_project(self, index) -> "List[Finding]":
        from unionml_tpu.analysis.project import function_cfg

        derived = derived_acquirers(index)
        derived_names = {fq.rsplit(".", 1)[-1] for fq in derived}
        findings: "List[Finding]" = []
        for summary in sorted(index.modules.values(), key=lambda s: s.path):
            for facts in sorted(
                summary.functions.values(), key=lambda f: (f.line, f.qualname)
            ):
                hints = function_hints(summary, facts)
                if not _relevant(hints, derived_names):
                    continue
                resolve = _make_resolver(index, summary, facts, derived, derived_names)
                cfg = function_cfg(summary, facts)
                sol = solve_resources(cfg, ResourceFlow(resolve))
                for var, proto_name, line in sorted(sol.at_raise):
                    proto = PROTOCOLS[proto_name]
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=facts.path,
                            line=line,
                            col=0,
                            message=(
                                f"'{var}' ({proto.noun}) acquired here can leak: a call "
                                f"between the acquire and its release may raise, and the "
                                f"exception path skips the release — {proto.fix}"
                            ),
                        )
                    )
        return findings
