"""TPU011 — Python-varying value passed into a static position of a jit call.

``jax.jit(fn, static_argnums=...)``/``static_argnames=...`` bakes the marked
argument into the compiled program: every DISTINCT value is a full trace +
XLA compile. That is the design (the value becomes a constant the compiler
can fold), and it is fine for genuinely enumerable values — a bool flag, a
bucketed length, a config enum. It becomes a production incident when the
call site feeds a value that varies per request or per loop iteration: a
loop index, ``len(prompt)``, a wall-clock or RNG draw, an f-string. Each
request then pays the full compile (87.6 s for BERT in this repo's bench) and
the AOT compile cache ROADMAP item 1 exists to build is defeated by an
unbounded key space — a *recompile storm*.

The per-file view cannot see this: the ``jax.jit`` wrap and the hot call site
are routinely in different modules. This rule uses the project index's jit
bindings (decorated functions, ``self._f = jax.jit(...)`` attributes,
module-level wraps — with their literal ``static_argnums``/``static_argnames``)
and checks every cross-module call site. An argument in a static position
flags when it is provably per-call-varying:

- a loop variable of an enclosing ``for`` (each iteration = one compile);
- ``len(...)`` of a function parameter (per-request length — bucket it);
- a ``time.*``/``random.*``/``uuid.*`` draw (unbounded key space);
- an f-string (unbounded string space).

Anything not provably varying — literals, config attributes, module
constants, plain parameters forwarded through — is left alone: a forwarded
parameter MAY vary, but flagging every forward would bury the storms under
noise, and the caller of that caller is checked at its own call sites.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import assign_target_names, call_target

_VARYING_CALL_PREFIXES = ("time.", "random.", "uuid.")
_VARYING_CALLS = {"time", "monotonic", "perf_counter"}  # from-imported spellings


class RecompileHazard(Rule):
    id = "TPU011"
    title = "Python-varying value in a static position of a jit-compiled call"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # the wrap and the call site are rarely in one file; index-only

    def check_project(self, index) -> "List[Finding]":
        findings: "List[Finding]" = []
        bindings = self._collect_bindings(index)
        if not bindings:
            return findings
        for facts in sorted(index.iter_functions(), key=lambda f: (f.path, f.line, f.qualname)):
            summary = index.modules.get(facts.module)
            if summary is None:
                continue
            for call, loop_vars in self._calls_with_loop_context(facts.node):
                raw = call_target(call)
                if raw is None:
                    continue
                binding = self._match(raw, facts, summary, index, bindings)
                if binding is None:
                    continue
                findings.extend(
                    self._check_call(call, loop_vars, facts, binding, index, summary)
                )
        return findings

    # ------------------------------------------------------------- bindings

    @staticmethod
    def _collect_bindings(index) -> "Dict[Tuple[str, Optional[str], str], object]":
        """(module, class-or-None, binding spelling) -> JitBinding, for every
        binding that has static positions."""
        out: "Dict[Tuple[str, Optional[str], str], object]" = {}
        for summary in index.modules.values():
            for binding in summary.jit_bindings:
                if binding.static_argnums or binding.static_argnames:
                    out.setdefault((summary.module, binding.cls, binding.binding), binding)
        return out

    @staticmethod
    def _match(raw, facts, summary, index, bindings):
        if raw.startswith(("self.", "cls.")):
            raw = "self." + raw.split(".", 1)[1]
            return bindings.get((facts.module, facts.cls, raw))
        # same module, module-level binding
        hit = bindings.get((facts.module, None, raw))
        if hit is not None:
            return hit
        # imported: alias -> fully-qualified module.symbol
        fq = index._resolve_alias(raw, summary)
        if fq is None:
            return None
        mod, _, sym = fq.rpartition(".")
        return bindings.get((mod, None, sym))

    # ------------------------------------------------------------ call walk

    @staticmethod
    def _calls_with_loop_context(func_node: ast.AST) -> "List[Tuple[ast.Call, Set[str]]]":
        """Every call in the function's own scope, with the set of enclosing
        for-loop target names active at that point."""
        out: "List[Tuple[ast.Call, Set[str]]]" = []

        def walk(node: ast.AST, loop_vars: "Set[str]") -> None:
            for child in ast.iter_child_nodes(node):
                visit(child, loop_vars)

        # dispatch per node, entered for walked children AND For-body
        # statements — a for-loop directly inside another for-loop must
        # re-enter the For branch so its own target variable accumulates
        def visit(node: ast.AST, loop_vars: "Set[str]") -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                visit(node.iter, loop_vars)  # the iter expr runs outside the loop body
                inner = loop_vars | set(assign_target_names(node.target))
                for stmt in node.body + node.orelse:
                    visit(stmt, inner)
                return
            record(node, loop_vars)
            walk(node, loop_vars)

        def record(node: ast.AST, loop_vars: "Set[str]") -> None:
            if isinstance(node, ast.Call):
                out.append((node, set(loop_vars)))

        walk(func_node, set())
        return out

    # ----------------------------------------------------------- the check

    def _check_call(self, call, loop_vars, facts, binding, index, summary) -> "List[Finding]":
        findings: "List[Finding]" = []
        static_exprs: "List[Tuple[str, ast.AST]]" = []
        for pos in binding.static_argnums:
            if pos < len(call.args):
                static_exprs.append((f"static position {pos}", call.args[pos]))
        target_params = self._target_params(binding, index, summary)
        for name in binding.static_argnames:
            for kw in call.keywords:
                if kw.arg == name:
                    static_exprs.append((f"static argument '{name}'", kw.value))
            if target_params is not None and name in target_params:
                pos = target_params.index(name)
                if target_params[:1] in (["self"], ["cls"]):
                    pos -= 1
                if 0 <= pos < len(call.args):
                    static_exprs.append((f"static argument '{name}'", call.args[pos]))
        for label, expr in static_exprs:
            reason = self._varying_reason(expr, loop_vars, facts.params)
            if reason is None:
                continue
            findings.append(
                self.finding(
                    facts.path,
                    expr,
                    f"{reason} flows into {label} of jit-compiled "
                    f"'{binding.target_raw or binding.binding}' (jit-bound at line {binding.line}) — every distinct "
                    "value triggers a full trace+compile and defeats the AOT compile cache; "
                    "bucket the value (pad to a fixed set) or make the argument traced",
                )
            )
        return findings

    @staticmethod
    def _target_params(binding, index, summary) -> "Optional[List[str]]":
        if not binding.target_raw:
            return None
        caller = None
        if binding.cls is not None:
            # resolve self._impl relative to the owning class
            cls = summary.classes.get(binding.cls)
            if cls is not None and binding.target_raw.startswith(("self.", "cls.")):
                bare = binding.target_raw.split(".", 1)[1]
                facts = summary.functions.get(f"{binding.cls}.{bare}")
                return list(facts.params) if facts is not None else None
        facts = index.resolve_call(binding.target_raw, summary, caller)
        return list(facts.params) if facts is not None else None

    @staticmethod
    def _varying_reason(expr: ast.AST, loop_vars: "Set[str]", params) -> "Optional[str]":
        if isinstance(expr, ast.Name) and expr.id in loop_vars:
            return f"loop variable '{expr.id}' (one compile per iteration)"
        if isinstance(expr, ast.JoinedStr):
            return "an f-string (unbounded static key space)"
        if isinstance(expr, ast.Call):
            target = call_target(expr)
            if target == "len" and expr.args:
                arg = expr.args[0]
                base = arg
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in params:
                    return f"len() of parameter '{base.id}' (per-request length)"
            if target is not None and (
                target.startswith(_VARYING_CALL_PREFIXES) or target in _VARYING_CALLS
            ):
                return f"'{target}()' (a new value every call)"
        return None
