"""TPU010 — lock-order cycle across methods and modules (potential deadlock).

The serving stack holds six-plus locks across three thread kinds (HTTP
handler threads, the engine decode loop, the autoscaler), and the deadlock
shape is never visible in one file: thread 1 takes ``ReplicaSet._scale_lock``
then reaches into an engine method that takes the engine's ``_lock``; thread
2 holds the engine ``_lock`` in the decode loop and calls back into a fleet
method that wants ``_scale_lock``. Each call site is locally reasonable; the
cycle only exists in the whole-program lock-acquisition graph — which is
exactly what Infer/RacerD-style interprocedural analysis builds, and what
this rule builds from the project index.

Construction: every function's recorded acquisitions carry the lock set held
at that point (``with self.<lock>:`` nesting, plus the ``*_locked``
convention — a ``*_locked`` method's body is charged with its class's lock).
An edge ``L -> M`` means some thread can acquire ``M`` while holding ``L``,
either by textual nesting or by calling (transitively, through the resolved
cross-module call graph) a function that acquires ``M``. Any cycle in that
directed graph is a potential deadlock; the finding reports BOTH acquisition
paths so the fix (a global lock order, or dropping one lock before taking
the other) is mechanical.

Out of scope, deliberately: re-acquiring the SAME lock (``L -> L``) — RLocks
are reentrant, Conditions are usually waited on, and call-graph
over-approximation would make self-edges mostly noise. Lock identity is by
declaring class (``module.Class.attr``) or module-global name — the standard
abstraction: two instances of one class rank identically in the lock order.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from unionml_tpu.analysis.engine import Finding, Rule


class LockOrderCycle(Rule):
    id = "TPU010"
    title = "lock-order cycle across the project's lock-acquisition graph"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # whole-program only: a single tree cannot hold a cross-module cycle

    def check_project(self, index) -> "List[Finding]":
        # edge (L, M) -> (witness text, anchor path, anchor line); first
        # witness in deterministic order wins
        edges: "Dict[Tuple[str, str], Tuple[str, str, int]]" = {}
        functions = sorted(index.iter_functions(), key=lambda f: (f.path, f.line, f.qualname))
        for facts in functions:
            summary = index.modules.get(facts.module)
            if summary is None:
                continue
            # textual nesting: `with A: ... with B:` inside one function
            for token, line, held in facts.acquisitions:
                inner = index.lock_node(token, summary, facts)
                if inner is None:
                    continue
                for held_token in held:
                    outer = index.lock_node(held_token, summary, facts)
                    if outer is None or outer == inner:
                        continue
                    witness = (
                        f"{facts.fq} acquires {inner} at {facts.path}:{line} "
                        f"while holding {outer}"
                    )
                    edges.setdefault((outer, inner), (witness, facts.path, line))
            # call-driven: holding L, call something that (transitively) takes M
            for call in facts.calls:
                if not call.held:
                    continue
                callee = index.resolve_call(call.raw, summary, facts)
                if callee is None or callee.fq == facts.fq:
                    continue
                for inner, (chain, acq_line) in sorted(index.transitive_acquisitions(callee).items()):
                    for held_token in call.held:
                        outer = index.lock_node(held_token, summary, facts)
                        if outer is None or outer == inner:
                            continue
                        via = " -> ".join(chain)
                        witness = (
                            f"{facts.fq} holds {outer} and calls {call.raw}() at "
                            f"{facts.path}:{call.line}; the chain {via} acquires {inner} "
                            f"({callee.path}:{acq_line})"
                        )
                        edges.setdefault((outer, inner), (witness, facts.path, call.line))
        return self._report_cycles(edges)

    # --------------------------------------------------------------- cycles

    def _report_cycles(
        self, edges: "Dict[Tuple[str, str], Tuple[str, str, int]]"
    ) -> "List[Finding]":
        graph: "Dict[str, List[str]]" = {}
        for outer, inner in edges:
            graph.setdefault(outer, []).append(inner)
            graph.setdefault(inner, [])
        for targets in graph.values():
            targets.sort()
        findings: "List[Finding]" = []
        reported: "set" = set()
        for start in sorted(graph):
            cycle = self._shortest_cycle(graph, start)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            pairs = [(cycle[i], cycle[(i + 1) % len(cycle)]) for i in range(len(cycle))]
            witnesses = [edges[pair][0] for pair in pairs]
            _, anchor_path, anchor_line = edges[pairs[0]]
            locks = " -> ".join(cycle + [cycle[0]])
            findings.append(
                Finding(
                    rule=self.id,
                    path=anchor_path,
                    line=anchor_line,
                    col=0,
                    message=f"lock-order cycle {locks}: "
                    + "; ".join(f"[path {i + 1}] {w}" for i, w in enumerate(witnesses))
                    + " — two threads taking these paths concurrently deadlock; impose one "
                    "global acquisition order or release the outer lock before the call",
                )
            )
        return findings

    @staticmethod
    def _shortest_cycle(graph: "Dict[str, List[str]]", start: str) -> "List[str] | None":
        """Shortest directed cycle through ``start`` (BFS back to start)."""
        queue: "List[List[str]]" = [[start]]
        seen = {start}
        while queue:
            path = queue.pop(0)
            for nxt in graph.get(path[-1], ()):
                if nxt == start:
                    return path
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append(path + [nxt])
        return None
