"""TPU006 — wall-clock ``time.time()`` used for duration or deadline arithmetic.

``time.time()`` steps under NTP corrections (and leaps at manual clock sets):
a duration measured as ``time.time() - t0`` can come out negative or minutes
long, and a deadline built as ``time.time() + timeout`` can fire early or
never — in serving/engine code that means bogus latency percentiles, spurious
deadline sheds, and drains that exit too soon. Elapsed time and deadlines must
use ``time.monotonic()`` (or ``time.perf_counter()`` for fine measurement),
which is what every other timing site in the serving stack already does.

Detection: within one scope, two *wall-clock-derived* values (a direct
``time.time()`` call, or a name assigned from an expression containing one)
meeting in a subtraction or an ordering comparison. Pairing is the point —
a lone ``time.time()`` recorded as a timestamp (job heartbeat files,
``deployed_at`` fields) is legitimate wall-clock use, and subtracting a
wall-clock value read from ANOTHER process (``time.time() - float(file)``) is
the one case monotonic cannot serve, so neither is flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import assign_target_names, call_target, iter_scope

_WALL_CLOCK = {"time.time", "time"}  # `time.time()` / `from time import time; time()`

_ORDERING = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


def _is_wall_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_target(node) in _WALL_CLOCK and not node.args


def _contains_wall_call(expr: ast.AST) -> bool:
    return any(_is_wall_call(node) for node in ast.walk(expr))


class WallClockDuration(Rule):
    id = "TPU006"
    title = "time.time() paired into duration/deadline arithmetic (use time.monotonic())"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        scopes: "List[ast.AST]" = [tree]
        scopes += [
            n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            findings.extend(self._check_scope(scope, path))
        return findings

    def _check_scope(self, scope: ast.AST, path: str) -> "List[Finding]":
        # names assigned from an expression containing time.time() anywhere in
        # this scope are wall-clock tainted (covers `t0 = time.time()` and the
        # deadline form `deadline = time.time() + timeout`)
        tainted: "Set[str]" = set()
        for node in iter_scope(scope):
            if isinstance(node, ast.Assign) and _contains_wall_call(node.value):
                for target in node.targets:
                    tainted.update(assign_target_names(target))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and node.value is not None:
                if _contains_wall_call(node.value):
                    tainted.update(assign_target_names(node.target))

        def derived(expr: ast.AST) -> bool:
            if _is_wall_call(expr):
                return True
            return isinstance(expr, ast.Name) and expr.id in tainted

        findings: "List[Finding]" = []
        for node in iter_scope(scope):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if derived(node.left) and derived(node.right):
                    findings.append(
                        self.finding(
                            path, node,
                            "duration measured by subtracting wall-clock time.time() values "
                            "— the result steps under NTP corrections; use time.monotonic()",
                        )
                    )
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if (
                    any(isinstance(op, _ORDERING) for op in node.ops)
                    and sum(1 for operand in operands if derived(operand)) >= 2
                ):
                    findings.append(
                        self.finding(
                            path, node,
                            "deadline arithmetic on wall-clock time.time() values — the "
                            "comparison fires early/late under NTP corrections; build "
                            "deadlines from time.monotonic()",
                        )
                    )
        return findings
