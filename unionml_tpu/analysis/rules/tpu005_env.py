"""TPU005 — bare numeric parse of an environment variable.

``int(os.environ.get("VAR", "0"))`` has a default for the UNSET case but none
for the garbage case: ``VAR=abc`` raises ``ValueError`` at whatever moment the
code happens to read it — for serve-path knobs that is import/export time in
``cli.py serve``, taking the whole service down over a typo'd deployment env.
The hardened pattern wraps the conversion in ``try/except ValueError`` with a
warn-and-fall-back (see :func:`unionml_tpu.defaults.env_int`), which this rule
recognizes as clean.

Detection: ``int(...)``/``float(...)`` whose argument reads
``os.environ[...]``/``os.environ.get(...)``/``os.getenv(...)`` — directly or
through a local name assigned from such a read in the same scope — outside any
``try`` whose handlers catch ``ValueError``/``TypeError``/``Exception``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import assign_target_names, call_target, dotted, iter_scope

_CATCHING = {"ValueError", "TypeError", "Exception", "BaseException", None}


def _reads_env(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_target(node) in ("os.environ.get", "os.getenv", "environ.get", "getenv"):
            return True
        if isinstance(node, ast.Subscript) and dotted(node.value) in ("os.environ", "environ"):
            return True
    return False


def _handler_names(handler: ast.ExceptHandler) -> "Set":
    if handler.type is None:
        return {None}
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    return {dotted(t).split(".")[-1] if dotted(t) else "" for t in types}


class BareEnvNumericParse(Rule):
    id = "TPU005"
    title = "environment variable parsed to a number without a garbage fallback"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        scopes: "List[ast.AST]" = [tree]
        scopes += [n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            findings.extend(self._check_scope(scope, path))
        return findings

    def _check_scope(self, scope: ast.AST, path: str) -> "List[Finding]":
        # names assigned from an env read anywhere in this scope are tainted
        tainted: "Set[str]" = set()
        for node in iter_scope(scope):
            if isinstance(node, ast.Assign) and _reads_env(node.value):
                for target in node.targets:
                    tainted.update(assign_target_names(target))
        findings: "List[Finding]" = []
        self._visit(scope, path, tainted, protected=False, findings=findings)
        return findings

    def _visit(self, node: ast.AST, path: str, tainted: "Set[str]", protected: bool, findings: "List[Finding]") -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                continue  # nested scopes get their own _check_scope pass
            if isinstance(child, ast.Try):
                catches = set()
                for handler in child.handlers:
                    catches.update(_handler_names(handler))
                guarded = protected or bool(catches & _CATCHING)
                for stmt in child.body:
                    self._visit(stmt, path, tainted, guarded, findings)
                for rest in (child.handlers, child.orelse, child.finalbody):
                    for stmt in rest:
                        self._visit(stmt, path, tainted, protected, findings)
                continue
            if isinstance(child, ast.Call) and not protected:
                target = call_target(child)
                if target in ("int", "float") and len(child.args) == 1:
                    arg = child.args[0]
                    is_env = _reads_env(arg) or (
                        isinstance(arg, ast.Name) and arg.id in tainted
                    )
                    if is_env:
                        findings.append(
                            self.finding(
                                path, child,
                                f"{target}() on an environment variable without a garbage "
                                "fallback — VAR=abc raises ValueError at read time; wrap in "
                                "try/except with a warn-and-default (defaults.env_int/env_float)",
                            )
                        )
            self._visit(child, path, tainted, protected, findings)
        return None
