"""TPU007 — ``*_locked`` helper called without holding the lock.

TPU003 polices direct attribute mutation, but it deliberately EXEMPTS methods
named ``*_locked``: their docstring contract is "caller holds the lock", and
the engine extracts its refcount/free-list bookkeeping — block allocator
returns, radix-cache pin/release/insert/eviction, slot finish/preempt — into
exactly such helpers. That trust has a caller-side hole: a ``*_locked``
helper invoked OUTSIDE a ``with self._lock:`` block mutates the same guarded
state TPU003 protects, with none of its scrutiny. The radix prefix cache
(serving/prefix_cache.py) widened this surface — the tree and the
``_free_blocks`` allocator are mutated exclusively through ``*_locked``
helpers, so one unlocked call site is a lost-update/corruption race on the
KV block pool.

This rule closes the hole: within a class that owns a
``threading.Lock``/``RLock``/``Condition`` attribute, every
``self._foo_locked(...)`` / ``cls._foo_locked(...)`` call must appear either
inside a ``with self.<lock>:`` block or inside another ``*_locked`` method
(the contract propagates to its caller).

Conventions honored (the codebase's existing idiom, mirroring TPU003):

- ``__init__``/``__new__``/``__del__``/``__post_init__`` are exempt —
  construction happens before the object is shared;
- calls on OTHER objects (``self.engine._foo_locked()``) are out of scope:
  the lock those helpers assume is the other object's, which a class-local
  analysis cannot see;
- classes without a lock attribute are out of scope — ``*_locked`` there is
  just a naming choice.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import LOCK_FACTORIES, call_target, self_attribute

_LOCK_FACTORIES = LOCK_FACTORIES

_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}


class UnlockedLockedHelperCall(Rule):
    id = "TPU007"
    title = "*_locked helper called without holding the lock"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, cls: ast.ClassDef, path: str) -> "List[Finding]":
        locks = self._lock_attributes(cls)
        if not locks:
            return []
        findings: "List[Finding]" = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS or method.name.endswith("_locked"):
                continue
            self._walk(method, method.name, locks, under_lock=False, findings=findings, path=path)
        return findings

    @staticmethod
    def _lock_attributes(cls: ast.ClassDef) -> "Set[str]":
        """Attributes assigned a Lock/RLock/Condition anywhere in the class
        (the same detection TPU003 uses)."""
        locks: "Set[str]" = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_target(node.value) in _LOCK_FACTORIES:
                    for target in node.targets:
                        attr = self_attribute(target)
                        if attr is not None and isinstance(target, ast.Attribute):
                            locks.add(attr)
        return locks

    def _walk(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        findings: "List[Finding]",
        path: str,
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                continue  # nested scopes: a closure's lock discipline is its own
            if isinstance(child, ast.With):
                holds = under_lock or any(
                    self_attribute(item.context_expr) in locks for item in child.items
                )
                for stmt in child.body:
                    self._walk(stmt, method, locks, holds, findings, path)
                continue
            self._record(child, method, locks, under_lock, findings, path)
            self._walk(child, method, locks, under_lock, findings, path)

    def _record(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        findings: "List[Finding]",
        path: str,
    ) -> None:
        if under_lock or not isinstance(node, ast.Call):
            return
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr.endswith("_locked")):
            return
        # self/cls receivers only: another object's *_locked helper assumes
        # ITS owner's lock, which this class-local analysis cannot track
        if not (isinstance(func.value, ast.Name) and func.value.id in ("self", "cls")):
            return
        findings.append(
            self.finding(
                path, node,
                f"'self.{func.attr}()' is called in {method}() without holding "
                f"'self.{sorted(locks)[0]}' — its name promises the caller holds the "
                "lock (TPU003 exempts it on that basis), so this call races every "
                "guarded mutation inside it",
            )
        )
