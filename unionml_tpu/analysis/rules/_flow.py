"""Shared machinery for the flow rules (TPU016-TPU019).

The four flow rules are all instances of one pattern: a gen/kill dataflow
problem over the per-function CFG (:mod:`unionml_tpu.analysis.cfg`), where
facts are outstanding obligations — an unreleased resource, an unrefunded
tenant charge, a held lock — and the rule fires when a fact reaches a place
it must not (the RAISE exit, a ``return``, a ``yield``).

This module holds the protocol table (which calls acquire what, and what
releases it), the prescan that lets warm project passes skip the ~95% of
functions that mention no protocol at all, and the two dataflow problems
(:class:`ResourceFlow`, :class:`LockFlow`) the rules instantiate.

Ownership-transfer ("escape") semantics, validated against the real tree:

* ``return``/``yield`` reading the variable — the caller/consumer owns it now
  (``RemoteHost._connect`` returning its ``HTTPConnection``).
* storing it into an attribute or subscript — it outlives the function by
  design (``self._slot_blocks[slot] = alloc``, ``session.pins = pins``).
* passing it as a call argument — handing it to another owner
  (``subprocess.Popen(..., stdout=log_file)``, ``_RemoteStream(conn)``).
  Receiver position (``conn.request(...)``) is use, not escape.
* rebinding or ``del`` — the name no longer refers to the resource.

Escapes kill the fact: once ownership has moved, leaking is some other
scope's bug, and flagging it here would just teach people to suppress.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from unionml_tpu.analysis.cfg import CFG, CFGNode
from unionml_tpu.analysis.dataflow import Problem, solve_forward
from unionml_tpu.analysis.rules._common import LOCK_FACTORIES, call_target, dotted, iter_scope

__all__ = [
    "PROTOCOLS",
    "Protocol",
    "ResourceFlow",
    "LockFlow",
    "acquire_proto_of_call",
    "derived_acquirers",
    "function_hints",
    "lock_token_of",
]


@dataclasses.dataclass(frozen=True)
class Protocol:
    name: str
    noun: str  #: human noun for messages
    fix: str  #: how to guarantee the release


PROTOCOLS: Dict[str, Protocol] = {
    "open-file": Protocol(
        "open-file", "file handle", "use `with open(...)` or close it in a try/finally"
    ),
    "socket": Protocol(
        "socket", "socket", "use `with socket.socket(...)` or close it in a try/finally"
    ),
    "http-conn": Protocol(
        "http-conn", "HTTP connection", "close it in a try/finally (or try/except + re-raise)"
    ),
    "kv-blocks": Protocol(
        "kv-blocks",
        "KV-cache block list",
        "return the blocks to the free list in a try/except before re-raising",
    ),
    "radix-pin": Protocol(
        "radix-pin",
        "pinned radix prefix blocks",
        "release the pins in a try/except before re-raising",
    ),
}

#: protocols whose release is ``<var>.close()``
CLOSE_PROTOS = frozenset({"open-file", "socket", "http-conn"})

#: resource fact: (variable, protocol name, acquisition line)
Fact = Tuple[str, str, int]


def acquire_proto_of_call(call: ast.Call) -> Optional[str]:
    """Protocol acquired by this call expression, if any (direct matchers)."""
    target = call_target(call)
    if target is None:
        return None
    last = target.rsplit(".", 1)[-1]
    if target == "open":
        return "open-file"
    if target == "socket.socket" or target.endswith(".socket.socket"):
        return "socket"
    if last in ("HTTPConnection", "HTTPSConnection"):
        return "http-conn"
    if (
        last == "pop"
        and isinstance(call.func, ast.Attribute)
        and "free_blocks" in (dotted(call.func.value) or "")
    ):
        return "kv-blocks"
    return None


def derived_acquirers(index) -> Dict[str, str]:
    """``FunctionFacts.fq -> protocol`` for one-hop acquire wrappers: functions
    whose body does ``return <direct acquire call>`` (``RemoteHost._connect``
    returning an ``HTTPConnection``).  A call to such a function acquires the
    same obligation as the call it wraps.

    Cached on the index — TPU016 and TPU019 both need the map, and the scan
    is gated on the prescan hints (a function with no direct acquire site
    cannot be returning one), so warm runs pay almost nothing."""
    cached = getattr(index, "_derived_acquirers", None)
    if cached is not None:
        return cached
    out: Dict[str, str] = {}
    for summary in index.modules.values():
        for facts in summary.functions.values():
            if not function_hints(summary, facts).protos:
                continue
            for node in iter_scope(facts.node):
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Call):
                    proto = acquire_proto_of_call(node.value)
                    if proto is not None:
                        out[facts.fq] = proto
                        break
    index._derived_acquirers = out
    return out


# ------------------------------------------------------------------- prescan


@dataclasses.dataclass
class FlowHints:
    """What one function mentions, from a single cheap AST walk — memoized on
    the module summary so warm runs skip the walk *and* everything downstream."""

    protos: FrozenSet[str] = frozenset()  #: protocols with a direct acquire site
    calls: FrozenSet[str] = frozenset()  #: raw call targets (for derived acquirers)
    has_pin: bool = False
    has_charge: bool = False
    has_yield: bool = False
    has_lock: bool = False


def function_hints(summary, facts) -> FlowHints:
    key = (facts.qualname, facts.line)
    hints = summary.flow_hints.get(key)
    if hints is None:
        hints = _scan_hints(facts.node)
        summary.flow_hints[key] = hints
    return hints


def _scan_hints(func: ast.AST) -> FlowHints:
    protos: Set[str] = set()
    calls: Set[str] = set()
    has_pin = has_charge = has_yield = has_lock = False
    for node in iter_scope(func):
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            has_yield = True
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            has_lock = True  # candidate; LockFlow decides if it is really a lock
        elif isinstance(node, ast.Call):
            proto = acquire_proto_of_call(node)
            if proto is not None:
                protos.add(proto)
            target = call_target(node)
            if target is not None:
                calls.add(target)
                last = target.rsplit(".", 1)[-1]
                if last == "pin":
                    has_pin = True
                elif last in ("try_admit", "charge"):
                    has_charge = True
                elif last == "acquire":
                    has_lock = True
    return FlowHints(
        protos=frozenset(protos),
        calls=frozenset(calls),
        has_pin=has_pin,
        has_charge=has_charge,
        has_yield=has_yield,
        has_lock=has_lock,
    )


# ------------------------------------------------------- resource dataflow


def _loaded_names(node: ast.AST) -> Set[str]:
    return {
        sub.id
        for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


def _stored_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, (ast.Store, ast.Del)):
            out.add(sub.id)
    return out


class ResourceFlow(Problem):
    """Outstanding acquire/release obligations (TPU016/TPU019).

    ``resolve`` maps an :class:`ast.Call` to the protocol it acquires through
    a one-hop wrapper (see :func:`derived_acquirers`); pass ``None`` when no
    index is available — direct matchers still apply.
    """

    def __init__(self, resolve=None) -> None:
        self._resolve = resolve
        self._memo: Dict[int, Tuple[Set[Fact], Set[Fact]]] = {}

    def _call_proto(self, call: ast.Call) -> Optional[str]:
        proto = acquire_proto_of_call(call)
        if proto is None and self._resolve is not None:
            proto = self._resolve(call)
        return proto

    def gen_kill(self, node: CFGNode):
        cached = self._memo.get(node.nid)
        if cached is not None:
            return cached
        gen: Set[Fact] = set()
        kill: Set[Fact] = set()
        kill_vars: Set[str] = set()  # (var, *) wildcards, expanded by the solver
        stmt = node.stmt
        if node.kind == "stmt" and stmt is not None:
            # -- acquires -------------------------------------------------
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                if (
                    len(targets) == 1
                    and isinstance(targets[0], ast.Name)
                    and stmt.value is not None
                ):
                    for call in ast.walk(stmt.value):
                        if isinstance(call, ast.Call):
                            proto = self._call_proto(call)
                            if proto is not None:
                                gen.add((targets[0].id, proto, node.line))
                                break
            for expr in node.exprs:
                for call in ast.walk(expr):
                    if not isinstance(call, ast.Call):
                        continue
                    func = call.func
                    method = func.attr if isinstance(func, ast.Attribute) else None
                    # arg-style acquire: <...radix...>.pin(name)
                    if (
                        method == "pin"
                        and "radix" in (dotted(func.value) or "")
                        and len(call.args) == 1
                        and isinstance(call.args[0], ast.Name)
                    ):
                        gen.add((call.args[0].id, "radix-pin", node.line))
                    # -- releases ------------------------------------------
                    if method == "close" and isinstance(func.value, ast.Name):
                        for proto in CLOSE_PROTOS:
                            kill.add((func.value.id, proto))
                    if (
                        method == "release"
                        and len(call.args) >= 1
                        and isinstance(call.args[0], ast.Name)
                    ):
                        kill.add((call.args[0].id, "radix-pin"))
                    if (
                        method in ("extend", "append")
                        and "free_blocks" in (dotted(func.value) or "")
                        and len(call.args) == 1
                        and isinstance(call.args[0], ast.Name)
                    ):
                        kill.add((call.args[0].id, "kv-blocks"))
                    # -- escape: passed as an argument (ownership transfer)
                    for arg in list(call.args) + [kw.value for kw in call.keywords]:
                        kill_vars |= _loaded_names(arg)
            # -- escape / rebind ------------------------------------------
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                for target in targets:
                    kill_vars |= _stored_names(target)  # rebind
                    if isinstance(target, (ast.Attribute, ast.Subscript)) and getattr(
                        stmt, "value", None
                    ) is not None:
                        kill_vars |= _loaded_names(stmt.value)  # outlives the function
            elif isinstance(stmt, ast.Delete):
                for target in stmt.targets:
                    kill_vars |= _stored_names(target)
            elif isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    kill_vars |= _loaded_names(stmt.value)  # caller owns it now
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                kill_vars |= _stored_names(stmt.target)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        kill_vars |= _stored_names(item.optional_vars)
            if node.is_yield:
                for expr in node.exprs:
                    for sub in ast.walk(expr):
                        if isinstance(sub, (ast.Yield, ast.YieldFrom)) and sub.value is not None:
                            kill_vars |= _loaded_names(sub.value)
        if kill_vars:
            for var in kill_vars:
                for proto in PROTOCOLS:
                    kill.add((var, proto))
        result = (gen, kill)
        self._memo[node.nid] = result
        return result

    def apply_kill(self, facts, kill):
        # kills are (var, proto); facts are (var, proto, line) — match prefix
        return {f for f in facts if (f[0], f[1]) not in kill}

    def assume(self, node, branch, facts):
        """Path sensitivity: on a branch where the variable is proven falsy
        (``if pins:`` not taken, ``if conn is None:`` taken) there is no
        resource behind the name — an empty pin list or a None handle carries
        no release obligation, so guarded-release idioms like
        ``if pins: release(pins)`` analyze clean on both branches."""
        stmt = node.stmt
        test = getattr(stmt, "test", None) if isinstance(stmt, (ast.If, ast.While)) else None
        if test is None:
            return facts
        falsy_var = None
        if isinstance(test, ast.Name):
            if branch == "false":
                falsy_var = test.id
        elif (
            isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)
        ):
            if branch == "true":
                falsy_var = test.operand.id
        elif (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and len(test.ops) == 1
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            if isinstance(test.ops[0], ast.Is) and branch == "true":
                falsy_var = test.left.id
            elif isinstance(test.ops[0], ast.IsNot) and branch == "false":
                falsy_var = test.left.id
        if falsy_var is None:
            return facts
        return {f for f in facts if f[0] != falsy_var}


def solve_resources(cfg: CFG, problem: ResourceFlow):
    return solve_forward(cfg, problem)


# ------------------------------------------------------------ lock dataflow


def lock_token_of(expr: ast.AST, lock_attrs: Set[str], module_locks: Set[str], local_types: Dict[str, str]) -> Optional[str]:
    """The lock identity of ``expr`` if it denotes a known lock, else None."""
    name = dotted(expr)
    if name is None:
        if isinstance(expr, ast.Call):
            target = call_target(expr)
            if target in LOCK_FACTORIES:
                return target  # `with threading.Lock():` — anonymous
        return None
    if name.startswith(("self.", "cls.")):
        attr = name.split(".", 1)[1]
        if "." not in attr and attr in lock_attrs:
            return name
        return None
    head = name.split(".", 1)[0]
    if name in module_locks or head in module_locks:
        return name
    if local_types.get(head) in LOCK_FACTORIES:
        return name
    return None


class LockFlow(Problem):
    """Which known locks are held (TPU018).  Facts are ``(token, line)``."""

    def __init__(self, lock_attrs: Set[str], module_locks: Set[str], local_types: Dict[str, str]) -> None:
        self._lock_attrs = lock_attrs
        self._module_locks = module_locks
        self._local_types = local_types

    def _token(self, expr: ast.AST) -> Optional[str]:
        return lock_token_of(expr, self._lock_attrs, self._module_locks, self._local_types)

    def _with_tokens(self, stmt: ast.AST) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        for item in stmt.items:
            token = self._token(item.context_expr)
            if token is not None:
                out.append((token, stmt.lineno))
        return out

    def gen_kill(self, node: CFGNode):
        gen: Set[Tuple[str, int]] = set()
        kill: Set[str] = set()  # lock tokens, matched against (token, line) facts
        stmt = node.stmt
        if node.kind == "stmt" and isinstance(stmt, (ast.With, ast.AsyncWith)):
            gen |= set(self._with_tokens(stmt))
        elif node.kind == "with_exit" and isinstance(stmt, (ast.With, ast.AsyncWith)):
            kill |= {token for token, _ in self._with_tokens(stmt)}
        elif node.kind == "stmt" and stmt is not None:
            for expr in node.exprs:
                for call in ast.walk(expr):
                    if not isinstance(call, ast.Call):
                        continue
                    func = call.func
                    if isinstance(func, ast.Attribute) and func.attr in ("acquire", "release"):
                        token = self._token(func.value)
                        if token is None:
                            continue
                        if func.attr == "acquire":
                            gen.add((token, node.line))
                        else:
                            kill.add(token)
        return gen, kill

    def apply_kill(self, facts, kill):
        return {f for f in facts if f[0] not in kill}
