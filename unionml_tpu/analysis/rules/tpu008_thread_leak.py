"""TPU008 — leaked engine thread: started in a closeable class, never joined.

The serving stack's lifecycle contract is that ``close()`` tears everything
down: the continuous engine joins its decode thread, the replica set joins its
autoscaler loop, the HTTP server drains its handlers. Elastic runtime resize
(disaggregated serving) multiplies the places a background thread gets
started — and a thread that outlives ``close()`` keeps dispatching against a
device pool (or a replica fleet) the owner believes is gone: the exact bug
class PR 3's sweep found live in the engine once already.

The rule: inside a class that defines ``close()``, every
``threading.Thread(...)`` must be *joinable from the object* —

- assigned to a ``self.<attr>`` on which ``.join(...)`` is called somewhere
  in the class (any method; the engine's lazily started ``_thread`` joined in
  ``close`` is the canonical idiom), or
- tracked into a ``self.<container>`` via ``.append(...)``/``.add(...)``
  (the fork-worker list pattern — the container's consumer joins), or
- a local that is ``.join()``-ed in the same method (scoped helper threads,
  like a warmup fan-out).

Flagged: a Thread assigned to an attribute no method ever joins, and a
fire-and-forget local/immediate ``threading.Thread(...).start()`` in a method
of a closeable class. ``daemon=True`` is NOT an exemption — the engine thread
is a daemon AND joined; daemonhood saves interpreter exit, not the live
``close()``-then-reuse sequence.

Out of scope (the usual conservative posture): classes without a ``close``
method (nothing promises teardown), module-level functions (no lifecycle
object to leak from), and threads created by other objects.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import call_target

_THREAD_FACTORIES = {"threading.Thread", "Thread"}
_TRACK_METHODS = {"append", "add", "appendleft"}
_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _ordered_scope(node: ast.AST):
    """``iter_scope`` in SOURCE order: the create→track→join dataflow below is
    order-sensitive, and the shared stack-based walker visits siblings in
    reverse."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, _SCOPE_NODES):
            yield from _ordered_scope(child)


def _is_thread_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_target(node) in _THREAD_FACTORIES


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"`` (None otherwise)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class LeakedEngineThread(Rule):
    id = "TPU008"
    title = "thread started in a closeable class but never joined/tracked"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, cls: ast.ClassDef, path: str) -> "List[Finding]":
        methods = [
            node for node in cls.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        if not any(method.name == "close" for method in methods):
            return []
        joined_attrs = self._joined_attrs(cls)
        findings: "List[Finding]" = []
        #: self attributes assigned a Thread anywhere in the class, keyed on
        #: the FIRST assignment node (the report site)
        thread_attrs: "Dict[str, ast.AST]" = {}
        for method in methods:
            findings.extend(
                self._check_method(method, thread_attrs, joined_attrs, path)
            )
        for attr, node in thread_attrs.items():
            if attr not in joined_attrs:
                findings.append(self.finding(
                    path, node,
                    f"threading.Thread assigned to self.{attr} in a class with close() "
                    f"but no method ever calls self.{attr}.join(...) — the thread "
                    "outlives close(); join it there (a daemon flag only covers "
                    "interpreter exit, not teardown-then-reuse)",
                ))
        return findings

    @staticmethod
    def _joined_attrs(cls: ast.ClassDef) -> "Set[str]":
        """Attributes ``.join(...)``-ed anywhere in the class, including via a
        local alias (``thread = self._thread; ... thread.join()`` — the
        engine-loop idiom that keeps the join outside the lock)."""
        joined: "Set[str]" = set()
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            #: local name -> self attribute it aliases, within this method
            aliases: "Dict[str, str]" = {}
            for node in ast.walk(method):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = node.targets[0]
                    attr = _self_attr_of(node.value)
                    if isinstance(target, ast.Name) and attr is not None:
                        aliases[target.id] = attr
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                ):
                    receiver = node.func.value
                    attr = _self_attr_of(receiver)
                    if attr is not None:
                        joined.add(attr)
                    elif isinstance(receiver, ast.Name) and receiver.id in aliases:
                        joined.add(aliases[receiver.id])
        return joined

    def _check_method(
        self,
        method: ast.AST,
        thread_attrs: "Dict[str, ast.AST]",
        joined_attrs: "Set[str]",
        path: str,
    ) -> "List[Finding]":
        findings: "List[Finding]" = []
        #: local names bound to a Thread in this method, with their creation
        #: node; names that get joined/tracked/stored are discharged
        locals_pending: "Dict[str, ast.AST]" = {}
        #: Thread(...) Call nodes consumed by an enclosing Assign handler —
        #: iter_scope revisits them as bare Calls, which must not re-report
        handled_calls: "Set[int]" = set()
        for node in _ordered_scope(method):
            if isinstance(node, ast.Assign) and _is_thread_call(node.value):
                handled_calls.add(id(node.value))
                handled = False
                for target in node.targets:
                    attr = _self_attr_of(target)
                    if attr is not None:
                        thread_attrs.setdefault(attr, node)
                        handled = True
                    elif isinstance(target, ast.Name):
                        locals_pending[target.id] = node
                        handled = True
                if not handled:
                    findings.append(self.finding(
                        path, node,
                        "threading.Thread stored where no join can reach it in a "
                        "class with close()",
                    ))
                continue
            if _is_thread_call(node) and id(node) not in handled_calls:
                findings.append(self.finding(
                    path, node,
                    "fire-and-forget threading.Thread in a class with close(): "
                    "nothing can ever join it — assign and join it in close(), "
                    "or track it in a joined container",
                ))
                continue
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                receiver = node.func.value
                if node.func.attr == "join" and isinstance(receiver, ast.Name):
                    locals_pending.pop(receiver.id, None)
                if node.func.attr in _TRACK_METHODS and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        # tracked into a container (self.<threads>.append(t)):
                        # the container's consumer owns the join
                        locals_pending.pop(arg.id, None)
            if isinstance(node, ast.Assign):
                # re-binding a pending local to self.<attr> promotes it to the
                # attribute contract; any other re-binding keeps it pending
                value = node.value
                if isinstance(value, ast.Name) and value.id in locals_pending:
                    for target in node.targets:
                        attr = _self_attr_of(target)
                        if attr is not None:
                            thread_attrs.setdefault(attr, locals_pending.pop(value.id))
                            break
        for name, node in locals_pending.items():
            findings.append(self.finding(
                path, node,
                f"thread {name!r} started in a method of a class with close() is "
                "neither joined here, stored on self, nor tracked in a container — "
                "it outlives close()",
            ))
        return findings
