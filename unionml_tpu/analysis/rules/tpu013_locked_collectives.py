"""TPU013 — blocking cross-process collective called while holding a lock.

Multi-host fleets (serving/cluster.py, unionml_tpu/distributed.py) add a new
failure shape the single-process rules cannot see: a CROSS-PROCESS
rendezvous. ``multihost_utils.sync_global_devices`` / ``broadcast_one_to_all``
/ ``process_allgather``, the ``jax.distributed`` barrier/KV waits, and the
fleet's own control-plane RPC helpers all block THIS process until every
peer (or the addressed worker) arrives. Held under a lock from
``_common.LOCK_FACTORIES`` the blast radius changes category: a one-host
stall (a peer wedged in XLA, a worker mid-restart) turns into every thread
on THIS host queueing behind the lock — and if any peer needs that lock's
owner to make progress before reaching its own collective, the whole fleet
deadlocks. The coordinator's posture is route-around-the-dead-host; a
collective under a lock is the one place that posture cannot save.

Scope (the TPU007/TPU010 conventions): within a class that owns a
``threading.Lock``/``RLock``/``Condition`` attribute, any flagged call
lexically inside a ``with self.<lock>:`` block — or anywhere inside a
``*_locked`` method, whose name promises the caller already holds the lock —
is a finding. Flagged calls:

- anything under ``multihost_utils.`` / ``jax.experimental.multihost_utils.``
  or the bare re-exports (``sync_global_devices``, ``broadcast_one_to_all``,
  ``process_allgather``);
- anything under ``jax.distributed.`` (initialize/shutdown and the KV-store
  client waits);
- the repo's own cross-process helpers: ``distributed.barrier`` /
  ``distributed.agree`` / ``distributed.allgather_ints`` (dotted or bare),
  and the cluster control-plane RPCs (``_call`` / ``_stream_call`` on a
  host handle, ``ping`` / ``probe`` on a remote host) — one wedged worker
  must cost that call, not the lock.

``__init__``-family methods are exempt (construction precedes sharing), and
classes without a lock attribute are out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import LOCK_FACTORIES, call_target, self_attribute

_EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}

#: dotted-name prefixes that are always cross-process rendezvous
_COLLECTIVE_PREFIXES = (
    "multihost_utils.",
    "jax.experimental.multihost_utils.",
    "jax.distributed.",
    "distributed.",  # unionml_tpu.distributed's barrier/agree/allgather_ints
)

#: exact names (bare imports of the multihost re-exports, and the repo's own
#: cross-process helpers) that block on a peer
_COLLECTIVE_NAMES = {
    "sync_global_devices",
    "broadcast_one_to_all",
    "process_allgather",
    "barrier",
    "agree",
    "allgather_ints",
}

#: method names whose receiver is a control-plane host handle — a blocking
#: RPC to one worker process (serving/cluster.py's RemoteHost surface)
_CONTROL_RPC_METHODS = {"_call", "_stream_call", "ping", "probe"}


class BlockingCollectiveUnderLock(Rule):
    id = "TPU013"
    title = "blocking cross-process collective while holding a lock"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, path))
        return findings

    def _check_class(self, cls: ast.ClassDef, path: str) -> "List[Finding]":
        locks = self._lock_attributes(cls)
        if not locks:
            return []
        findings: "List[Finding]" = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS:
                continue
            # a *_locked method's contract is "caller holds the lock": its whole
            # body is an under-lock region
            under = method.name.endswith("_locked")
            self._walk(method, method.name, locks, under, findings, path)
        return findings

    @staticmethod
    def _lock_attributes(cls: ast.ClassDef) -> "Set[str]":
        locks: "Set[str]" = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_target(node.value) in LOCK_FACTORIES:
                    for target in node.targets:
                        attr = self_attribute(target)
                        if attr is not None and isinstance(target, ast.Attribute):
                            locks.add(attr)
        return locks

    def _walk(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        findings: "List[Finding]",
        path: str,
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                continue  # nested scopes run later, possibly lock-free
            if isinstance(child, ast.With):
                holds = under_lock or any(
                    self_attribute(item.context_expr) in locks for item in child.items
                )
                for stmt in child.body:
                    self._walk(stmt, method, locks, holds, findings, path)
                continue
            self._record(child, method, locks, under_lock, findings, path)
            self._walk(child, method, locks, under_lock, findings, path)

    def _record(
        self,
        node: ast.AST,
        method: str,
        locks: "Set[str]",
        under_lock: bool,
        findings: "List[Finding]",
        path: str,
    ) -> None:
        if not under_lock or not isinstance(node, ast.Call):
            return
        label = self._collective_label(node)
        if label is None:
            return
        findings.append(
            self.finding(
                path, node,
                f"'{label}' blocks on another PROCESS while {method}() holds "
                f"'self.{sorted(locks)[0]}' — a stalled peer turns this host's lock into "
                "a fleet-wide stall (and a deadlock if the peer needs this lock's owner "
                "to progress); move the collective/RPC outside the locked section",
            )
        )

    @staticmethod
    def _collective_label(node: ast.Call) -> "str | None":
        target = call_target(node)
        if target is not None:
            for prefix in _COLLECTIVE_PREFIXES:
                if target.startswith(prefix):
                    return target
            if target in _COLLECTIVE_NAMES:
                return target
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _COLLECTIVE_NAMES:
                return func.attr
            if func.attr in _CONTROL_RPC_METHODS and target is None:
                # a control RPC on a computed receiver (self.hosts[i].probe(...)):
                # the dotted form was already covered above
                return func.attr
            if func.attr in _CONTROL_RPC_METHODS and target is not None and "." in target:
                return target
        return None
