"""TPU015 — network retry loop with neither an attempt bound nor a backoff.

The fleet's fault-tolerance layer (serving/cluster.py) made retrying control
RPCs a first-class idiom — and an unbounded one is the classic outage
amplifier: a loop that re-invokes a network call (``_call`` /
``_stream_call`` / ``ping`` / ``probe``, ``urlopen``, an ``http.client``
``getresponse``) as fast as exceptions arrive turns one dead worker into a
busy-spinning coordinator thread and a self-inflicted connect storm the
moment the worker returns. Every retry loop must carry at least one of the
two brakes the repo's own helper (``RemoteHost._call_retry``, the bounded
decorrelated-jitter envelope) carries both of: a **bounded attempt count**
or a **sleep/backoff between attempts**.

Detection (deliberately structural, not name-guessing):

- a ``while`` loop whose body (its own scope — nested function bodies run
  elsewhere) contains a flagged network call is a finding **unless** the
  loop is *bounded* — its test contains a comparison (``attempt < n``,
  ``time.monotonic() < deadline``), or the body carries a guarded exit
  (``if attempt >= n: break``/``raise``/``return``) whose test **dominates**
  the loop back edge (checked on the CFG: the bound must run on *every*
  iteration — one buried under a rare-path ``if`` bounds nothing) — or
  *paced* — an ``Event.wait``-style ``.wait(...)`` call in the test, or a
  ``time.sleep`` / ``asyncio.sleep`` / ``.wait(...)`` / ``*backoff*``-named
  call in the body;
- a ``for`` loop is inherently bounded by its iterable, EXCEPT over
  ``itertools.count()`` / ``cycle()`` (spelled dotted or bare), which get
  the same test.

Walking a finite host list re-invoking ``probe`` per host stays clean (one
attempt per host is not a retry), as does a poll loop that sleeps.
"""

from __future__ import annotations

import ast
from typing import List

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.rules._common import call_target

#: method/function names whose invocation is a network round trip (the
#: control-plane RPC surface + the stdlib HTTP client verbs)
_NETWORK_NAMES = {"_call", "_stream_call", "ping", "probe", "urlopen", "getresponse"}

#: dotted prefixes that are always network receivers
_NETWORK_PREFIXES = ("http.client.", "urllib.request.")

#: calls that pace a loop (the "has a backoff" brake)
_PACING_NAMES = {"sleep"}  # time.sleep / asyncio.sleep / bare sleep

#: unbounded iterator constructors: a for-loop over one never ends
_UNBOUNDED_ITERS = {"count", "cycle", "itertools.count", "itertools.cycle"}


def _is_network_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    target = call_target(node)
    if target is not None:
        if any(target.startswith(prefix) for prefix in _NETWORK_PREFIXES):
            return True
        if target.rsplit(".", 1)[-1] in _NETWORK_NAMES:
            return True
    func = node.func
    return isinstance(func, ast.Attribute) and func.attr in _NETWORK_NAMES


def _is_pacing_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None
    )
    if name is None:
        return False
    return name in _PACING_NAMES or name == "wait" or "backoff" in name.lower()


def _own_scope_nodes(loop_body: "List[ast.stmt]") -> "List[ast.AST]":
    """Every node of the loop body's own scope (nested defs/lambdas/classes
    excluded — their bodies run at some other time, under some other pacing)."""
    out: "List[ast.AST]" = []
    stack: "List[ast.AST]" = list(loop_body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class UnboundedNetworkRetry(Rule):
    id = "TPU015"
    title = "network retry loop with neither an attempt bound nor a backoff"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if isinstance(node, ast.While):
                self._check_while(node, path, findings)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._check_for(node, path, findings)
        return findings

    def _check_while(self, loop: ast.While, path: str, findings: "List[Finding]") -> None:
        # a comparison in the test is a loop-variant bound (attempt counter,
        # deadline); an Event.wait-paced test throttles by construction
        bounded = any(isinstance(n, ast.Compare) for n in ast.walk(loop.test))
        paced = any(_is_pacing_call(n) for n in ast.walk(loop.test))
        if bounded or paced:
            return
        self._judge_body(loop, loop.body, path, findings)

    def _check_for(self, loop: "ast.For | ast.AsyncFor", path: str, findings: "List[Finding]") -> None:
        iterable = loop.iter
        if not isinstance(iterable, ast.Call):
            return
        target = call_target(iterable)
        if target not in _UNBOUNDED_ITERS:
            return  # a finite iterable bounds the loop
        self._judge_body(loop, loop.body, path, findings)

    def _judge_body(
        self, loop: ast.AST, body: "List[ast.stmt]", path: str, findings: "List[Finding]"
    ) -> None:
        nodes = _own_scope_nodes(body)
        network = next((n for n in nodes if _is_network_call(n)), None)
        if network is None:
            return
        if any(_is_pacing_call(n) for n in nodes):
            return
        if self._dominating_bound(loop):
            return
        label = call_target(network) or (
            network.func.attr if isinstance(network.func, ast.Attribute) else "network call"
        )
        findings.append(
            self.finding(
                path, network,
                f"'{label}' is re-invoked by an unbounded loop with no sleep/backoff — "
                "one dead peer becomes a busy-spin and a connect storm when it returns; "
                "bound the attempts (for attempt in range(n)) or pace the loop "
                "(decorrelated-jitter sleep, like RemoteHost._call_retry)",
            )
        )

    @staticmethod
    def _dominating_bound(loop: ast.AST) -> bool:
        """True when the loop body carries a guarded exit — an ``if`` whose
        test compares (``attempt >= max_attempts``) and whose taken branch
        leaves the loop (``break``/``raise``/``return``) — that **dominates**
        every back edge of the loop, i.e. the bound test actually runs on
        every iteration.  A bound check buried under a rare-path ``if`` (only
        tested when some flag flips) bounds nothing and does not count."""
        from unionml_tpu.analysis.cfg import build_cfg
        from unionml_tpu.analysis.dataflow import dominators

        holder = ast.Module(body=[loop], type_ignores=[])
        cfg = build_cfg(holder)
        header = next((n for n in cfg.statement_nodes() if n.stmt is loop), None)
        if header is None:
            return False
        backs = [src for src, dst in cfg.back_edges if dst == header.nid]
        if not backs:
            return False

        def _is_bound_node(n) -> bool:
            if n.stmt is None or n.stmt is loop or not isinstance(n.stmt, ast.If):
                return False
            if not any(isinstance(x, ast.Compare) for x in ast.walk(n.stmt.test)):
                return False
            return any(
                isinstance(x, (ast.Break, ast.Raise, ast.Return))
                for b in n.stmt.body
                for x in ast.walk(b)
            )

        bound_nids = {n.nid for n in cfg.statement_nodes() if _is_bound_node(n)}
        if not bound_nids:
            return False
        dom = dominators(cfg)
        return all(bound_nids & dom[src] for src in backs)
