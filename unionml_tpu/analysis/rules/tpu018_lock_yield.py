"""TPU018 — a generator yields while holding a lock.

A ``yield`` hands control to the consumer, and the consumer decides when —
or whether — the generator resumes.  If the generator is inside ``with
self._lock:`` at that point, the lock stays held across the suspension: a
slow HTTP client draining a token stream serializes every other thread that
needs the lock, and a consumer that abandons the iterator without closing it
holds the lock until GC finalizes the frame.  That is the stream-iterator
deadlock shape: TPU003 sees the mutation is locked (fine), TPU013 sees no
collective under the lock (fine), and neither can express "the lock's
critical section contains a suspension point".

Lock identity reuses the index's discovery: class lock attributes (through
the MRO), module-level locks, and locals assigned from ``LOCK_FACTORIES``.
Held-ness is the :class:`~unionml_tpu.analysis.rules._flow.LockFlow` dataflow
— ``with`` acquires at entry and releases at the CFG's ``with_exit`` node on
every path, explicit ``.acquire()``/``.release()`` pairs gen/kill — so a
yield *between* ``release`` and re-``acquire`` is correctly clean.  The fix
is always the same: snapshot under the lock, yield outside it.
"""

from __future__ import annotations

import ast
from typing import List, Set

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.dataflow import solve_forward
from unionml_tpu.analysis.rules._flow import LockFlow, function_hints


class LockHeldAcrossYield(Rule):
    id = "TPU018"
    title = "generator yields while holding a lock"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # flow analysis runs in the project pass (CFGs are cached there)

    def check_project(self, index) -> "List[Finding]":
        from unionml_tpu.analysis.project import function_cfg

        findings: "List[Finding]" = []
        for summary in sorted(index.modules.values(), key=lambda s: s.path):
            for facts in sorted(
                summary.functions.values(), key=lambda f: (f.line, f.qualname)
            ):
                hints = function_hints(summary, facts)
                if not (hints.has_yield and hints.has_lock):
                    continue
                lock_attrs: "Set[str]" = set()
                if facts.cls is not None:
                    cls = summary.classes.get(facts.cls)
                    if cls is not None:
                        for candidate in index.class_mro(cls):
                            lock_attrs |= candidate.lock_attrs
                problem = LockFlow(lock_attrs, summary.module_locks, facts.local_types)
                cfg = function_cfg(summary, facts)
                sol = solve_forward(cfg, problem)
                for node in cfg.statement_nodes():
                    if not node.is_yield or not sol.reachable(node.nid):
                        continue
                    for token, line in sorted(sol.in_facts(node.nid)):
                        findings.append(
                            Finding(
                                rule=self.id,
                                path=facts.path,
                                line=node.line,
                                col=0,
                                message=(
                                    f"yield while holding lock '{token}' (acquired line "
                                    f"{line}): the consumer controls when this generator "
                                    f"resumes, so the lock is held for an unbounded time "
                                    f"— snapshot under the lock and yield outside it"
                                ),
                            )
                        )
        return findings
