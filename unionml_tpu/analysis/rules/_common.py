"""Shared AST helpers for tpu-lint rules (stdlib ``ast`` only)."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

#: names that compile a function for device execution when used as a decorator
#: or called with the function as first argument
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}

#: constructors whose result is a mutual-exclusion lock (shared by TPU003,
#: TPU007, TPU010, and the project index's per-class lock discovery)
LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as ``"a.b.c"`` (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_target(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def iter_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s OWN scope: descend the tree but do not enter nested
    function/class/lambda bodies — their statements belong to other scopes."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(child))


def literal_argnums(node: Optional[ast.AST]) -> Optional[Tuple[int, ...]]:
    """``donate_argnums=0`` or ``=(0, 2)`` as a tuple of ints; None when the
    value is absent or not a literal (a variable donate_argnums — e.g. gated on
    ``debug_disable_donation`` — cannot be analyzed and must not be guessed)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for element in node.elts:
            if not (
                isinstance(element, ast.Constant)
                and isinstance(element.value, int)
                and not isinstance(element.value, bool)
            ):
                return None
            out.append(element.value)
        return tuple(out)
    return None


def jit_wrap_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)``/``pjit(...)`` call itself, if ``node`` is one."""
    if isinstance(node, ast.Call) and call_target(node) in JIT_NAMES:
        return node
    return None


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit``, ``@jit``, ``@pjit``, ``@jax.jit(...)``, or
    ``@(functools.)partial(jax.jit, ...)``."""
    if dotted(dec) in JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        if call_target(dec) in JIT_NAMES:
            return True
        if call_target(dec) in ("partial", "functools.partial") and dec.args:
            return dotted(dec.args[0]) in JIT_NAMES
    return False


def assign_target_names(node: ast.AST) -> List[str]:
    """Flattened simple/dotted names bound by an assignment target."""
    out: List[str] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for element in node.elts:
            out.extend(assign_target_names(element))
    elif isinstance(node, ast.Starred):
        out.extend(assign_target_names(node.value))
    else:
        name = dotted(node)
        if name is not None:
            out.append(name)
    return out


def self_attribute(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``"x"`` (one level only; ``self.x.y`` resolves to ``"x"``)."""
    while isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id in ("self", "cls"):
            return node.attr
        node = node.value
    return None
