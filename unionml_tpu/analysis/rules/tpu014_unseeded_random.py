"""TPU014 — unseeded randomness in benchmarks and workloads.

Determinism is the replay contract: the traffic engine's whole premise
(workloads/scenarios.py) is that the same spec + seed produce byte-identical
traces, and every bench lane's keep-best accretion assumes a rerun measures
the SAME workload. A draw from the process-global RNG — ``random.random()``,
``np.random.randint(...)`` — silently breaks both: the global state is shared
across modules and threads, so an unrelated import or an extra warmup call
shifts every subsequent draw, and "same seed" stops meaning "same trace".

The fixed forms in-tree: a local ``random.Random(seed)`` instance, a
``np.random.default_rng(seed)`` Generator, or ``jax.random`` keys — all draws
hang off an object whose state the caller owns.

The rule: inside ``benchmarks/`` and ``unionml_tpu/workloads/`` (path-scoped
— library code that legitimately wants entropy, like request-id minting, is
out of scope), flag any CALL of a draw function on the ``random`` module
(``random.random``/``randint``/``choice``/``shuffle``/``uniform``/
``expovariate``/...) or on ``np.random``/``numpy.random`` (``rand``/
``randn``/``randint``/``choice``/``permutation``/``normal``/...). NOT
flagged: constructors (``random.Random(seed)``, ``np.random.default_rng``,
``np.random.Generator``, ``random.SystemRandom``), method calls on rng
instances (``rng.integers(...)``), and ``jax.random.*`` (explicitly keyed —
the root name is ``jax``, not ``random``). Conservative posture: aliased
imports (``import random as rnd``) are not chased — the in-tree idiom never
aliases these modules.
"""

from __future__ import annotations

import ast
from pathlib import PurePath
from typing import List

from unionml_tpu.analysis.engine import Finding, Rule

#: draw functions on the stdlib ``random`` module's GLOBAL instance
_RANDOM_DRAWS = {
    "random", "randint", "randrange", "getrandbits", "choice", "choices",
    "shuffle", "sample", "uniform", "triangular", "betavariate", "expovariate",
    "gammavariate", "gauss", "lognormvariate", "normalvariate", "vonmisesvariate",
    "paretovariate", "weibullvariate", "randbytes", "binomialvariate", "seed",
}

#: draw functions on numpy's legacy GLOBAL RandomState (np.random.*)
_NP_DRAWS = {
    "rand", "randn", "randint", "random", "random_sample", "ranf", "sample",
    "choice", "permutation", "shuffle", "uniform", "normal", "standard_normal",
    "poisson", "exponential", "beta", "gamma", "binomial", "bytes", "integers",
    "laplace", "lognormal", "multinomial", "geometric", "seed",
}

#: the directories the determinism contract governs (path segments)
_SCOPED_SEGMENTS = ("benchmarks", "workloads")


def _in_scope(path: str) -> bool:
    return any(segment in PurePath(path).parts for segment in _SCOPED_SEGMENTS)


class UnseededRandomness(Rule):
    id = "TPU014"
    title = "unseeded global-RNG draw in benchmarks/workloads"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        if not _in_scope(path):
            return []
        findings: "List[Finding]" = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            func = node.func
            target = self._global_draw(func)
            if target is not None:
                findings.append(self.finding(
                    path, node,
                    f"{target} draws from the process-global RNG — determinism is "
                    "the replay/bench contract (same seed, same trace); draw from a "
                    "local random.Random(seed) or np.random.default_rng(seed) instead",
                ))
        return findings

    @staticmethod
    def _global_draw(func: ast.Attribute) -> "str | None":
        """``random.<draw>`` or ``np.random.<draw>``/``numpy.random.<draw>``
        -> the dotted name; None for anything else (rng instances, jax.random,
        constructors)."""
        # random.<fn>(...): the receiver is the bare name `random`
        if isinstance(func.value, ast.Name) and func.value.id == "random":
            if func.attr in _RANDOM_DRAWS:
                return f"random.{func.attr}"
            return None
        # np.random.<fn>(...) / numpy.random.<fn>(...)
        if (
            isinstance(func.value, ast.Attribute)
            and func.value.attr == "random"
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id in ("np", "numpy")
        ):
            if func.attr in _NP_DRAWS:
                return f"{func.value.value.id}.random.{func.attr}"
        return None
