"""TPU017 — tenant token-bucket charge whose exception path skips the refund.

PR 10's admission contract is "never double-charge, never charge on shed":
``TenantRegistry.try_admit`` debits the tenant's request bucket exactly when
it returns ``None`` (admitted); a non-``None`` return is a retry-after with
the buckets untouched.  Everything that happens between a successful charge
and the request actually entering the batch — grammar compilation, queue
mutation, thread spawn — can raise; if the exception propagates without a
refund, the tenant paid for a request that was never served.  Under
sustained load that is a slow quota leak: a tenant's effective rate sinks
below its configured floor and no counter explains why.

The dataflow: an assignment ``r = <registry>.try_admit(...)`` (or
``.charge(...)``) generates a charge fact.  The fact is *path-sensitive*:
``try_admit`` charged only when its result is ``None``, so on the branch
where ``r is not None`` the assume-transfer kills the fact — which is what
keeps the canonical ``if r is not None: raise TenantThrottled(...)`` shed
path clean.  A ``.refund(...)`` call kills the fact.  Any charge fact
reaching the RAISE exit is a finding.

``charge_tokens`` (generated-token debt, settled post-hoc by design) is
deliberately NOT a charge here: it records actual consumption after the
fact, and refunding it would un-count work that was really done.

``test_*`` functions are exempt: the refund contract binds production
callers that sit between a charge and the batch, not tests asserting on
bucket math — a failing ``assert`` after ``try_admit`` tears the whole
registry down, so there is no tenant left to over-bill.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from unionml_tpu.analysis.engine import Finding, Rule
from unionml_tpu.analysis.dataflow import Problem
from unionml_tpu.analysis.rules._common import call_target
from unionml_tpu.analysis.rules._flow import function_hints

#: method names that debit a tenant bucket up front (refundable on failure)
CHARGE_METHODS = frozenset({"try_admit", "charge"})

#: charge fact: (result variable or "", charge line)
Fact = Tuple[str, int]


def _charge_call(node: ast.AST):
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in CHARGE_METHODS
    ):
        return node
    return None


class ChargeFlow(Problem):
    def gen_kill(self, node):
        gen: "Set[Fact]" = set()
        kill: "Set[str]" = set()
        stmt = node.stmt
        if node.kind != "stmt" or stmt is None:
            return gen, kill
        var = ""
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            var = stmt.targets[0].id
        for expr in node.exprs:
            for sub in ast.walk(expr):
                if _charge_call(sub) is not None:
                    gen.add((var, node.line))
                elif isinstance(sub, ast.Call):
                    # refund-by-name: `registry.refund(t)` or a wrapper like
                    # `_refund_admission(registry, t)` — guarded-refund
                    # helpers keep the None-registry correlation out of the
                    # dataflow's sight, so the name is the contract
                    target = call_target(sub) or ""
                    if "refund" in target.rsplit(".", 1)[-1]:
                        kill.add("*")
        return gen, kill

    def apply_kill(self, facts, kill):
        return set() if "*" in kill else facts

    def assume(self, node, branch, facts):
        """Kill the charge fact on branches where the charge did not happen:
        ``try_admit`` returned non-None (a retry-after) exactly when it did
        NOT debit the bucket."""
        stmt = node.stmt
        test = getattr(stmt, "test", None) if isinstance(stmt, (ast.If, ast.While)) else None
        if test is None:
            return facts
        not_charged_var = None  # var proven non-None on this branch
        if (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and len(test.ops) == 1
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            if isinstance(test.ops[0], ast.IsNot) and branch == "true":
                not_charged_var = test.left.id
            elif isinstance(test.ops[0], ast.Is) and branch == "false":
                not_charged_var = test.left.id
        elif isinstance(test, ast.Name) and branch == "true":
            # `if retry_after:` — truthy retry-after means not charged
            not_charged_var = test.id
        if not_charged_var is None:
            return facts
        return {f for f in facts if f[0] != not_charged_var}


class ChargeWithoutRefund(Rule):
    id = "TPU017"
    title = "tenant charge reaches an exception exit without a refund"

    def check(self, tree: ast.Module, path: str) -> "List[Finding]":
        return []  # flow analysis runs in the project pass (CFGs are cached there)

    def check_project(self, index) -> "List[Finding]":
        from unionml_tpu.analysis.project import function_cfg
        from unionml_tpu.analysis.dataflow import solve_forward

        findings: "List[Finding]" = []
        for summary in sorted(index.modules.values(), key=lambda s: s.path):
            for facts in sorted(
                summary.functions.values(), key=lambda f: (f.line, f.qualname)
            ):
                if not function_hints(summary, facts).has_charge:
                    continue
                if facts.qualname.rsplit(".", 1)[-1].startswith("test_"):
                    continue  # see module docstring: the contract binds production callers
                cfg = function_cfg(summary, facts)
                sol = solve_forward(cfg, ChargeFlow())
                for var, line in sorted(sol.at_raise):
                    label = f"'{var}'" if var else "the charge"
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=facts.path,
                            line=line,
                            col=0,
                            message=(
                                f"tenant bucket charged here ({label}) and an exception "
                                f"path exits without a refund — the tenant pays for a "
                                f"request that was never served; refund in an `except` "
                                f"and re-raise"
                            ),
                        )
                    )
        return findings
