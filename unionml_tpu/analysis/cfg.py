"""Per-function control-flow graphs with exception edges.

tpu-lint's original rules are syntactic: they walk the AST and pattern-match
statements in source order.  That is blind to *paths* — an ``alloc`` whose
``free`` sits three statements later looks fine even if a call in between can
raise and skip the release forever.  This module builds a statement-level CFG
per function so the dataflow framework (:mod:`unionml_tpu.analysis.dataflow`)
can reason about what happens on every path, including exceptional ones.

Design notes
------------

* **Granularity** — one :class:`CFGNode` per simple statement, plus one per
  compound-statement *header* (the ``if``/``while`` test, the ``for`` iterable,
  the ``with`` context expressions).  ``node.exprs`` holds only the
  expressions evaluated *at that node*, never the nested body.
* **Synthetic nodes** — every CFG has ``entry``, ``exit`` (normal function
  exit: explicit ``return`` or falling off the end) and ``raise_node`` (the
  function terminating with an uncaught exception).  ``with`` blocks get a
  ``with_exit`` node modelling ``__exit__`` — reached on normal completion,
  exceptions, and abrupt exits, which is exactly the guaranteed-release
  semantics.  ``try`` blocks with handlers get a ``dispatch`` node that fans
  out to each handler and, when no handler is a catch-all, onward to the
  enclosing handler/finally/RAISE.
* **Edge kinds** — ``next`` (sequential), ``true``/``false`` (branch taken /
  not taken; the test expression is available via ``node.stmt``), ``exc``
  (exception propagation) and ``back`` (loop back edge, also recorded in
  ``CFG.back_edges``).
* **``finally`` threading** — a ``finally`` body is duplicated per
  continuation kind that crosses it (normal fall-through, ``return``,
  ``break``, ``continue``, exception), the "splitting-style" modelling
  CPython's own compiler uses.  The merge-style alternative (one shared copy,
  fringe routed per kind) is cheaper but bleeds dataflow facts between
  continuations: a fact that is live only on the normal path would flow
  through the shared ``finally`` and out along the exception edge, producing
  phantom leak reports.  ``with`` gets the same treatment — one ``with_exit``
  node per continuation kind.
* **May-raise** — a node can raise iff it contains a :class:`ast.Call`, or is
  a ``raise``/``assert`` statement.  Attribute access, subscripts etc. are
  deliberately ignored: the rules built on this care about calls into the
  serving stack, and tighter may-raise sets keep the sweep signal clean.
* **Generators** — any node whose expressions contain ``yield``/``yield from``
  is marked ``is_yield``: a suspension point at which the consumer may never
  resume us, so anything held across it is held indefinitely.

Construction cost is tracked in a module-level accumulator so
``benchmarks/bench_lint.py`` can report ``cfg_build_ms`` without threading a
timer through every rule (:func:`consume_build_time_ms`).
"""

from __future__ import annotations

import ast
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CFG",
    "CFGNode",
    "build_cfg",
    "consume_build_time_ms",
    "NEXT",
    "TRUE",
    "FALSE",
    "EXC",
    "BACK",
]

NEXT = "next"
TRUE = "true"
FALSE = "false"
EXC = "exc"
BACK = "back"

#: Edge list type: ``(source node id, edge kind)`` pairs waiting for a target.
Edge = Tuple[int, str]

_build_time_ns = 0

#: calls modelled as never raising: monotonic clock reads have no failure
#: mode worth an exception edge, and they are pervasive in `finally` blocks
#: (timing instrumentation) where a spurious exc edge would make every
#: release look skippable
_NO_RAISE_CALLS = frozenset(
    {
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "time.time",
    }
)


def consume_build_time_ms() -> float:
    """Return accumulated CFG construction time in ms and reset the counter."""
    global _build_time_ns
    ms = _build_time_ns / 1e6
    _build_time_ns = 0
    return ms


class CFGNode:
    """A single CFG node; ``kind`` is one of ``entry``/``exit``/``raise``/
    ``stmt``/``dispatch``/``handler``/``with_exit``."""

    __slots__ = ("nid", "kind", "stmt", "exprs", "succs", "preds", "line", "is_yield", "may_raise")

    def __init__(
        self,
        nid: int,
        kind: str,
        stmt: Optional[ast.AST] = None,
        exprs: Sequence[ast.AST] = (),
        line: int = 0,
    ) -> None:
        self.nid = nid
        self.kind = kind
        self.stmt = stmt
        self.exprs = [e for e in exprs if e is not None]
        self.succs: List[Edge] = []
        self.preds: List[Edge] = []
        self.line = line or getattr(stmt, "lineno", 0)
        self.is_yield = any(
            isinstance(sub, (ast.Yield, ast.YieldFrom))
            for e in self.exprs
            for sub in ast.walk(e)
        )
        self.may_raise = isinstance(stmt, (ast.Raise, ast.Assert)) or any(
            isinstance(sub, ast.Call) and _dotted(sub.func) not in _NO_RAISE_CALLS
            for e in self.exprs
            for sub in ast.walk(e)
        )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        label = type(self.stmt).__name__ if self.stmt is not None else ""
        return f"<CFGNode {self.nid} {self.kind} {label} L{self.line}>"


class CFG:
    """Control-flow graph for one function."""

    def __init__(self, func: ast.AST) -> None:
        self.func = func
        self.nodes: Dict[int, CFGNode] = {}
        self.entry = 0
        self.exit = 0
        self.raise_node = 0
        self.back_edges: List[Tuple[int, int]] = []

    def node(self, nid: int) -> CFGNode:
        return self.nodes[nid]

    def statement_nodes(self) -> List[CFGNode]:
        return [n for n in self.nodes.values() if n.kind not in ("entry", "exit", "raise")]


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


_CATCH_ALL = {"Exception", "BaseException"}


def _handler_is_catch_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    for t in types:
        name = _dotted(t).rsplit(".", 1)[-1]
        if name in _CATCH_ALL:
            return True
    return False


class _Frame:
    __slots__ = ("type", "dispatch", "breaks", "continues", "pending")

    def __init__(self, type_: str, dispatch: int = -1) -> None:
        self.type = type_
        self.dispatch = dispatch
        self.breaks: List[Edge] = []
        self.continues: List[Edge] = []
        # finally frames: continuation kind -> edges entering the finally
        self.pending: Dict[str, List[Edge]] = {}


class _Builder:
    def __init__(self, func: ast.AST) -> None:
        self.cfg = CFG(func)
        self._next_id = 0
        self.frames: List[_Frame] = []
        entry = self._new("entry", line=getattr(func, "lineno", 0))
        exit_n = self._new("exit")
        raise_n = self._new("raise")
        self.cfg.entry = entry.nid
        self.cfg.exit = exit_n.nid
        self.cfg.raise_node = raise_n.nid

    # ------------------------------------------------------------------ utils

    def _new(
        self,
        kind: str,
        stmt: Optional[ast.AST] = None,
        exprs: Sequence[ast.AST] = (),
        line: int = 0,
    ) -> CFGNode:
        node = CFGNode(self._next_id, kind, stmt, exprs, line)
        self._next_id += 1
        self.cfg.nodes[node.nid] = node
        return node

    def _connect(self, edges: Sequence[Edge], target: int) -> None:
        tgt = self.cfg.nodes[target]
        for src, kind in edges:
            self.cfg.nodes[src].succs.append((target, kind))
            tgt.preds.append((src, kind))
            if kind == BACK:
                self.cfg.back_edges.append((src, target))

    def _route(self, kind: str, edges: Sequence[Edge]) -> None:
        """Route abrupt-exit ``edges`` (kind ``return``/``break``/``continue``/
        ``raise``) through enclosing frames to their ultimate target."""
        if not edges:
            return
        for fr in reversed(self.frames):
            if fr.type == "finally":
                fr.pending.setdefault(kind, []).extend(edges)
                return
            if kind == "raise" and fr.type == "handler":
                self._connect(edges, fr.dispatch)
                return
            if kind in ("break", "continue") and fr.type == "loop":
                (fr.breaks if kind == "break" else fr.continues).extend(edges)
                return
        if kind == "raise":
            self._connect(edges, self.cfg.raise_node)
        else:
            self._connect(edges, self.cfg.exit)

    def _stmt_node(self, stmt: ast.stmt, exprs: Sequence[ast.AST], fringe: Sequence[Edge]) -> CFGNode:
        node = self._new("stmt", stmt, exprs)
        self._connect(fringe, node.nid)
        if node.may_raise:
            self._route("raise", [(node.nid, EXC)])
        return node

    # ------------------------------------------------------------ statements

    def build(self) -> CFG:
        body = getattr(self.cfg.func, "body", [])
        fringe = self._stmts(body, [(self.cfg.entry, NEXT)])
        self._connect(fringe, self.cfg.exit)
        return self.cfg

    def _stmts(self, stmts: Sequence[ast.stmt], fringe: Sequence[Edge]) -> List[Edge]:
        cur = list(fringe)
        for stmt in stmts:
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, fringe: List[Edge]) -> List[Edge]:
        if isinstance(stmt, ast.If):
            return self._if(stmt, fringe)
        if isinstance(stmt, (ast.While,)):
            return self._while(stmt, fringe)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, fringe)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, fringe)
        if isinstance(stmt, ast.Try) or (
            hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)
        ):
            return self._try(stmt, fringe)
        if isinstance(stmt, ast.Return):
            node = self._stmt_node(stmt, [stmt.value], fringe)
            self._route("return", [(node.nid, NEXT)])
            return []
        if isinstance(stmt, ast.Raise):
            node = self._new("stmt", stmt, [stmt.exc, stmt.cause])
            self._connect(fringe, node.nid)
            self._route("raise", [(node.nid, EXC)])
            return []
        if isinstance(stmt, ast.Break):
            node = self._new("stmt", stmt)
            self._connect(fringe, node.nid)
            self._route("break", [(node.nid, NEXT)])
            return []
        if isinstance(stmt, ast.Continue):
            node = self._new("stmt", stmt)
            self._connect(fringe, node.nid)
            self._route("continue", [(node.nid, NEXT)])
            return []
        if isinstance(stmt, ast.Assert):
            node = self._stmt_node(stmt, [stmt.test, stmt.msg], fringe)
            return [(node.nid, NEXT)]
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            return self._match(stmt, fringe)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            # Opaque: nested scopes get their own CFG; decorators may call.
            node = self._stmt_node(stmt, list(stmt.decorator_list), fringe)
            return [(node.nid, NEXT)]
        # Simple statements: Assign/AugAssign/AnnAssign/Expr/Delete/Import/...
        exprs = [v for v in ast.iter_child_nodes(stmt) if isinstance(v, ast.expr)]
        node = self._stmt_node(stmt, exprs, fringe)
        return [(node.nid, NEXT)]

    def _if(self, stmt: ast.If, fringe: List[Edge]) -> List[Edge]:
        node = self._stmt_node(stmt, [stmt.test], fringe)
        out = self._stmts(stmt.body, [(node.nid, TRUE)])
        if stmt.orelse:
            out = out + self._stmts(stmt.orelse, [(node.nid, FALSE)])
        else:
            out = out + [(node.nid, FALSE)]
        return out

    def _while(self, stmt: ast.While, fringe: List[Edge]) -> List[Edge]:
        node = self._stmt_node(stmt, [stmt.test], fringe)
        frame = _Frame("loop")
        self.frames.append(frame)
        body_fringe = self._stmts(stmt.body, [(node.nid, TRUE)])
        self.frames.pop()
        self._connect([(src, BACK) for src, _ in body_fringe], node.nid)
        self._connect([(src, BACK) for src, _ in frame.continues], node.nid)
        out: List[Edge] = list(frame.breaks)
        if stmt.orelse:
            out = out + self._stmts(stmt.orelse, [(node.nid, FALSE)])
        else:
            out = out + [(node.nid, FALSE)]
        return out

    def _for(self, stmt: ast.For, fringe: List[Edge]) -> List[Edge]:
        node = self._stmt_node(stmt, [stmt.iter, stmt.target], fringe)
        frame = _Frame("loop")
        self.frames.append(frame)
        body_fringe = self._stmts(stmt.body, [(node.nid, TRUE)])
        self.frames.pop()
        self._connect([(src, BACK) for src, _ in body_fringe], node.nid)
        self._connect([(src, BACK) for src, _ in frame.continues], node.nid)
        out: List[Edge] = list(frame.breaks)
        if stmt.orelse:
            out = out + self._stmts(stmt.orelse, [(node.nid, FALSE)])
        else:
            out = out + [(node.nid, FALSE)]
        return out

    def _with(self, stmt: ast.With, fringe: List[Edge]) -> List[Edge]:
        exprs: List[ast.AST] = []
        for item in stmt.items:
            exprs.append(item.context_expr)
            if item.optional_vars is not None:
                exprs.append(item.optional_vars)
        node = self._stmt_node(stmt, exprs, fringe)
        frame = _Frame("finally")
        self.frames.append(frame)
        body_fringe = self._stmts(stmt.body, [(node.nid, NEXT)])
        self.frames.pop()
        frame.pending.setdefault("normal", []).extend(body_fringe)
        out: List[Edge] = []
        for kind, edges in frame.pending.items():
            # one __exit__ node per continuation kind (splitting-style):
            # facts live only on the normal completion path must not bleed
            # onto the exception continuation through a shared exit node
            exit_node = self._new("with_exit", stmt, line=stmt.lineno)
            self._connect(edges, exit_node.nid)
            if kind == "normal":
                out = [(exit_node.nid, NEXT)]
            else:
                self._route(kind, [(exit_node.nid, EXC if kind == "raise" else NEXT)])
        return out

    def _try(self, stmt: ast.Try, fringe: List[Edge]) -> List[Edge]:
        fin_frame: Optional[_Frame] = None
        if stmt.finalbody:
            fin_frame = _Frame("finally")
            self.frames.append(fin_frame)
        dispatch: Optional[CFGNode] = None
        if stmt.handlers:
            dispatch = self._new("dispatch", stmt, line=stmt.lineno)
            self.frames.append(_Frame("handler", dispatch=dispatch.nid))
        body_fringe = self._stmts(stmt.body, fringe)
        if stmt.handlers:
            self.frames.pop()
        if stmt.orelse:
            body_fringe = self._stmts(stmt.orelse, body_fringe)
        after: List[Edge] = list(body_fringe)
        if dispatch is not None:
            catch_all = False
            for handler in stmt.handlers:
                hnode = self._new(
                    "handler", handler, [handler.type], line=handler.lineno
                )
                self._connect([(dispatch.nid, EXC)], hnode.nid)
                after.extend(self._stmts(handler.body, [(hnode.nid, NEXT)]))
                if _handler_is_catch_all(handler):
                    catch_all = True
            if not catch_all:
                self._route("raise", [(dispatch.nid, EXC)])
        if fin_frame is None:
            return after
        self.frames.pop()
        fin_frame.pending.setdefault("normal", []).extend(after)
        out: List[Edge] = []
        for kind, edges in fin_frame.pending.items():
            # splitting-style finally: one copy of the finalbody per
            # continuation kind, so facts from the normal path cannot bleed
            # onto the exception/return/break continuations (and vice versa)
            fin_fringe = self._stmts(stmt.finalbody, list(edges))
            if kind == "normal":
                out = list(fin_fringe)
            else:
                self._route(
                    kind,
                    [(src, EXC if kind == "raise" else k) for src, k in fin_fringe],
                )
        return out

    def _match(self, stmt: "ast.Match", fringe: List[Edge]) -> List[Edge]:
        node = self._stmt_node(stmt, [stmt.subject], fringe)
        out: List[Edge] = [(node.nid, FALSE)]
        for case in stmt.cases:
            out.extend(self._stmts(case.body, [(node.nid, TRUE)]))
        return out


def build_cfg(func: ast.AST) -> CFG:
    """Build the CFG for one ``FunctionDef``/``AsyncFunctionDef`` (or any node
    with a ``body`` of statements)."""
    global _build_time_ns
    start = time.perf_counter_ns()
    try:
        return _Builder(func).build()
    finally:
        _build_time_ns += time.perf_counter_ns() - start
