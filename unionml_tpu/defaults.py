"""Default execution settings.

Parity: reference unionml/defaults.py:5 defines ``DEFAULT_RESOURCES = Resources(cpu="1",
mem="1Gi")`` (a flytekit/k8s pod request). Our analog describes the host + TPU footprint
a stage asks the scheduler for.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Resources:
    """Resource request attached to a :class:`unionml_tpu.stage.Stage`.

    ``accelerator`` names a TPU slice topology (e.g. ``"v5e-1"``, ``"v5e-8"``); ``None``
    means host-only (CPU) execution, which is the default for data-plumbing stages.
    """

    cpu: str = "1"
    mem: str = "1Gi"
    accelerator: str | None = None
    chips: int = 0


DEFAULT_RESOURCES = Resources()

#: Environment variable used by ``serve``/``load_from_env`` — name kept identical to the
#: reference so existing user scripts keep working (reference unionml/cli.py:188-201).
MODEL_PATH_ENV_VAR = "UNIONML_MODEL_PATH"
