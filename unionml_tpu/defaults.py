"""Default execution settings.

Parity: reference unionml/defaults.py:5 defines ``DEFAULT_RESOURCES = Resources(cpu="1",
mem="1Gi")`` (a flytekit/k8s pod request). Our analog describes the host + TPU footprint
a stage asks the scheduler for.
"""

from __future__ import annotations

import dataclasses
import os

from unionml_tpu._logging import logger


@dataclasses.dataclass(frozen=True)
class Resources:
    """Resource request attached to a :class:`unionml_tpu.stage.Stage`.

    ``accelerator`` names a TPU slice topology (e.g. ``"v5e-1"``, ``"v5e-8"``); ``None``
    means host-only (CPU) execution, which is the default for data-plumbing stages.
    """

    cpu: str = "1"
    mem: str = "1Gi"
    accelerator: str | None = None
    chips: int = 0


DEFAULT_RESOURCES = Resources()

#: Environment variable used by ``serve``/``load_from_env`` — name kept identical to the
#: reference so existing user scripts keep working (reference unionml/cli.py:188-201).
MODEL_PATH_ENV_VAR = "UNIONML_MODEL_PATH"

# --------------------------------------------------------------------- overload
# Serving-stack overload protection (serving/overload.py). The reference
# outsourced all of this to uvicorn/Flyte; a TPU-native engine owns it. Every
# knob here is overridable per-app (ServingApp.configure_overload) and from the
# CLI (`serve --max-inflight/--deadline-ms/--max-deadline-ms/--drain-timeout`).

#: concurrent requests executing handlers before the HTTP layer sheds with 429.
SERVE_MAX_INFLIGHT = 256

#: micro-batcher admission queue bound (requests waiting to join a dispatch);
#: a full queue sheds with 429 instead of growing without bound.
SERVE_QUEUE_MAXSIZE = 1024

#: continuous-batching engine waiting-queue bound (prompts waiting for a free
#: decode slot) — ahead of the fixed slot pool itself.
SERVE_MAX_WAITING = 256

#: server-default per-request deadline (ms); a request still queued past it is
#: shed with 503, one mid-handler is cancelled. ``X-Request-Deadline-Ms`` lets
#: a client tighten (or, up to the max below, extend) it per request.
SERVE_DEFAULT_DEADLINE_MS = 30_000.0

#: ceiling on client-requested deadlines (ms): a client cannot pin server
#: resources longer than this no matter what header it sends.
SERVE_MAX_DEADLINE_MS = 300_000.0

#: seconds a SIGTERM-initiated drain waits for in-flight requests and streams
#: to finish before the process exits anyway.
SERVE_DRAIN_TIMEOUT_S = 30.0

#: ``Retry-After`` seconds attached to 429/503 shed responses.
SERVE_RETRY_AFTER_S = 1

#: env var carrying the ``serve --dp-replicas`` override: the CLI exports it
#: BEFORE the app module imports, so engines built at import time (or lazily at
#: first request) see it without any app code changes.
SERVE_DP_REPLICAS_ENV_VAR = "UNIONML_TPU_DP_REPLICAS"

# ------------------------------------------------------------ stall-free admission
# Chunked-admission knobs for the continuous-batching engine
# (serving/continuous.py): an arriving prompt's prefill is sliced into
# fixed-size chunks interleaved with decode dispatches (Sarathi-style
# chunked-prefill scheduling), so a long prompt no longer freezes every
# resident stream for its whole prefill. Same export pattern as
# SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets these before the app module
# imports, and the engine reads them at construction.

#: admission prefill slice width in tokens; 0 = unset (fall back to
#: ``GenerationConfig.prefill_chunk``, else monolithic admission).
SERVE_ADMIT_CHUNK_ENV_VAR = "UNIONML_TPU_ADMIT_CHUNK"

#: prefill tokens the engine may run per iteration between decode dispatches;
#: 0 = unset (one admission chunk per iteration).
SERVE_PREFILL_BUDGET_ENV_VAR = "UNIONML_TPU_PREFILL_BUDGET"

#: concurrent partially-prefilled admissions; 0 = unset (one at a time).
SERVE_MAX_ADMISSIONS_ENV_VAR = "UNIONML_TPU_MAX_ADMISSIONS"

#: 1 = enable the radix prefix cache (automatic cross-request KV reuse over
#: paged blocks, serving/prefix_cache.py) on paged continuous engines; 0/unset
#: = off, which keeps the engine byte-for-byte the pre-cache one. Same
#: early-export contract as the admission knobs.
SERVE_PREFIX_CACHE_ENV_VAR = "UNIONML_TPU_PREFIX_CACHE"

# ------------------------------------------------------- disaggregated serving
# Prefill/decode role split + elastic resize for the replica fleet
# (serving/replicas.py, docs/serving.md "Disaggregated and elastic serving").
# Same early-export contract as SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets
# these before the app module imports, and the ReplicaSet resolves them at
# construction — existing apps disaggregate with zero code changes.

#: replica role assignment, e.g. ``prefill=1,decode=3`` (roles: prefill /
#: decode / mixed; counts sum to the fleet size). Unset/empty = every replica
#: mixed (today's symmetric fleet). Garbage warns and falls back to symmetric.
SERVE_REPLICA_ROLES_ENV_VAR = "UNIONML_TPU_REPLICA_ROLES"

#: prompt-length threshold (tokens) above which an admission routes to a
#: prefill-role replica and its finished KV hands off to a decode replica;
#: 0 (the default) disaggregates every admission once roles are configured.
SERVE_PREFILL_THRESHOLD_ENV_VAR = "UNIONML_TPU_PREFILL_THRESHOLD"

#: autoscaler high watermark on per-replica scheduling load (the engine's
#: token-weighted ``load()`` averaged over the fleet); 0 = autoscaler off.
SERVE_AUTOSCALE_HIGH_ENV_VAR = "UNIONML_TPU_AUTOSCALE_HIGH"

#: autoscaler low watermark (scale down below it); 0 = never scale down.
SERVE_AUTOSCALE_LOW_ENV_VAR = "UNIONML_TPU_AUTOSCALE_LOW"

#: seconds between autoscaler evaluations of the windowed rates.
SERVE_AUTOSCALE_INTERVAL_S_ENV_VAR = "UNIONML_TPU_AUTOSCALE_INTERVAL_S"
SERVE_AUTOSCALE_INTERVAL_S = 10.0

#: fleet-size floor the autoscaler may never drain below.
SERVE_MIN_REPLICAS_ENV_VAR = "UNIONML_TPU_MIN_REPLICAS"

#: fleet-size ceiling; 0 = bounded by the spare submeshes/devices available.
SERVE_MAX_REPLICAS_ENV_VAR = "UNIONML_TPU_MAX_REPLICAS"

# -------------------------------------------------------- cold start / AOT preload
# Compile-cache + AOT-program-store knobs (compile_cache.py, serving/aot.py,
# docs/serving.md "Cold start and AOT preload"). Same early-export contract as
# SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets these before the app module
# imports, so engines built at import time preload too.

#: persistent XLA compilation cache directory (a path, "1" for the default
#: location, or an off-flag) — honored at package import by compile_cache.py;
#: `serve --compile-cache DIR` re-exports it for reload/fork children.
SERVE_COMPILE_CACHE_ENV_VAR = "UNIONML_TPU_COMPILE_CACHE"

#: AOT program store for serving executables: a directory path, a truthy flag
#: ("1"/"true"/"yes"/"on") for the default location, or an off-flag
#: (""/"0"/"false"/"no"/"off"/unset). With the store on, engine/Generator
#: warmup loads serialized executables instead of compiling (load-before-
#: compile), and every compile it does pay is serialized back for the next
#: cold process. An unusable directory warns and degrades to plain jit.
SERVE_AOT_PRELOAD_ENV_VAR = "UNIONML_TPU_AOT_PRELOAD"

# ------------------------------------------------------------ quantized serving
# Serve-time quantization knobs (docs/serving.md "Quantized serving"). Decode is
# HBM-bandwidth bound and the KV cache dominates resident memory at scale:
# int8 weights and int8 paged KV roughly halve bytes-per-step and roughly
# double resident streams per chip. Same early-export contract as
# SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets these before the app module
# imports, and Generators built by app code resolve them at construction —
# existing apps opt into quantized serving with zero code changes.

#: "int8" = weight-only int8 for serving Generators (ops/quant.py: per-channel
#: symmetric, dequant fused in-jit so int8 is what crosses HBM); "none"/unset =
#: full precision. Garbage values warn and fall back (never crash serve at
#: app-import time); explicit API calls still raise the Generator's own
#: "unsupported quantize mode" ValueError.
SERVE_QUANTIZE_ENV_VAR = "UNIONML_TPU_QUANTIZE"

#: "int8" = int8 KV cache (per-(position, head) symmetric scales — dense rows
#: and paged pools both, models/generate.init_cache/init_paged_cache);
#: "none"/unset = the compute dtype. Same warn-and-fall-back contract.
SERVE_KV_CACHE_DTYPE_ENV_VAR = "UNIONML_TPU_KV_CACHE_DTYPE"

# ------------------------------------------------------------- multi-tenant QoS
# Tenancy knobs (serving/tenancy.py, docs/serving.md "Multi-tenant QoS"). Same
# early-export contract as SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets these
# before the app module imports, and the serving app builds its TenantRegistry
# from them at construction. Neither set = tenancy off (byte-for-byte today's
# anonymous-and-equal serving stack).

#: path to a tenants.json (per-tenant weights, req/s + generated-tokens/s
#: bucket rates, default priority tier, api-key -> tenant mapping). A missing
#: or malformed file warns and degrades to --default-tenant-rate only.
SERVE_TENANT_CONFIG_ENV_VAR = "UNIONML_TPU_TENANT_CONFIG"

#: requests/s bucket rate for identified tenants NOT named in the config file
#: (anonymous traffic is never bucket-limited); 0/unset = unlimited.
SERVE_DEFAULT_TENANT_RATE_ENV_VAR = "UNIONML_TPU_DEFAULT_TENANT_RATE"

# ----------------------------------------------------------- multi-process fleets
# jax.distributed bootstrap knobs (unionml_tpu/distributed.py) shared by TRAIN
# (job_runner joining a slice) and SERVE (serving/cluster.py's worker
# processes). Same early-export contract as SERVE_DP_REPLICAS_ENV_VAR: the
# serve CLI exports them before the app module imports, and the launcher sets
# them on every worker it spawns.

#: coordinator address (``host:port``) for ``jax.distributed.initialize``;
#: unset = single-process (the bootstrap is a no-op).
DISTRIBUTED_COORDINATOR_ENV_VAR = "UNIONML_TPU_COORDINATOR"

#: total processes in the slice/fleet (1 = single process).
DISTRIBUTED_NUM_PROCESSES_ENV_VAR = "UNIONML_TPU_NUM_PROCESSES"

#: this process's id in ``[0, num_processes)``.
DISTRIBUTED_PROCESS_ID_ENV_VAR = "UNIONML_TPU_PROCESS_ID"

#: rendezvous directory for the serving fleet's control plane
#: (serving/cluster.py): each worker announces its loopback control-server
#: address as a ``host-<id>.json`` file there, and the coordinator connects by
#: polling it. Unset = ``.unionml_fleet`` under the working directory.
FLEET_DIR_ENV_VAR = "UNIONML_TPU_FLEET_DIR"

#: per-host role spec for the fleet coordinator (``prefill=1,decode=1`` at
#: HOST granularity — the cross-host analog of SERVE_REPLICA_ROLES_ENV_VAR);
#: unset/empty = every host mixed. Garbage warns and degrades to symmetric.
FLEET_HOST_ROLES_ENV_VAR = "UNIONML_TPU_HOST_ROLES"

# ----------------------------------------------------------- fleet fault tolerance
# Host-lifecycle / failover / fault-injection knobs (serving/cluster.py,
# serving/faults.py, docs/serving.md "Fault tolerance"). Same early-export
# contract as SERVE_DP_REPLICAS_ENV_VAR: the serve CLI sets these before the
# app module imports, and the coordinator/worker read them at construction.

#: a deterministic fault plan (serving/faults.py): a path to a plan JSON, or
#: the JSON inline (starts with ``{``). Unset = no injection. A garbage value
#: warns and degrades to no plan — chaos must be opt-in, never accidental.
SERVE_FAULT_PLAN_ENV_VAR = "UNIONML_TPU_FAULT_PLAN"

#: seconds between coordinator reconciliation ticks (lease heartbeat,
#: suspect/dead re-probes, rendezvous-dir announce scans).
FLEET_PROBE_INTERVAL_S_ENV_VAR = "UNIONML_TPU_PROBE_INTERVAL_S"
FLEET_PROBE_INTERVAL_S = 1.0

#: consecutive successful probes a returning host must pass in probation
#: before it takes traffic again.
FLEET_PROBATION_PROBES_ENV_VAR = "UNIONML_TPU_PROBATION_PROBES"
FLEET_PROBATION_PROBES = 2

#: consecutive probe failures that move a suspect host to dead.
FLEET_DEAD_AFTER_PROBES_ENV_VAR = "UNIONML_TPU_DEAD_AFTER_PROBES"
FLEET_DEAD_AFTER_PROBES = 3

#: coordinator heartbeat-lease TTL (seconds): workers treat a lease older
#: than this as an expired coordinator and the lowest-id live worker promotes.
FLEET_LEASE_TTL_S_ENV_VAR = "UNIONML_TPU_LEASE_TTL_S"
FLEET_LEASE_TTL_S = 5.0


def distributed_coordinator() -> "str | None":
    """The ``jax.distributed`` coordinator address (``host:port``); None =
    single-process. Read at bootstrap time (job_runner start, serve start),
    after the CLI/launcher export — the :func:`serve_dp_replicas` contract."""
    raw = os.environ.get(DISTRIBUTED_COORDINATOR_ENV_VAR)
    if raw is None or not raw.strip():
        return None
    return raw.strip()


def distributed_num_processes() -> int:
    """Total processes in the slice/fleet; garbage warns and degrades to 1
    (single-process) instead of crashing the bootstrap — the env_int
    contract."""
    return env_int(DISTRIBUTED_NUM_PROCESSES_ENV_VAR, 1, minimum=1)


def distributed_process_id() -> int:
    """This process's id in ``[0, num_processes)``; garbage warns and degrades
    to 0 — a mis-set worker then fails loudly at ``jax.distributed``
    rendezvous (duplicate id) rather than silently joining wrong."""
    return env_int(DISTRIBUTED_PROCESS_ID_ENV_VAR, 0, minimum=0)


def fleet_dir() -> str:
    """The serving fleet's control-plane rendezvous directory
    (``UNIONML_TPU_FLEET_DIR``); defaults to ``.unionml_fleet`` under the
    working directory so an emulated local fleet needs zero configuration."""
    raw = os.environ.get(FLEET_DIR_ENV_VAR)
    if raw is None or not raw.strip():
        return ".unionml_fleet"
    return raw.strip()


def serve_fault_plan() -> "str | None":
    """The fault-plan spec (``UNIONML_TPU_FAULT_PLAN``): a path or inline
    JSON; None = no injection. Validity is the consumer's concern —
    ``FaultPlan.from_env`` warns and degrades on garbage (the serve-export
    contract), never crashes serve at app-import time."""
    raw = os.environ.get(SERVE_FAULT_PLAN_ENV_VAR)
    if raw is None or not raw.strip():
        return None
    return raw.strip()


def fleet_probe_interval_s() -> float:
    """Seconds between coordinator reconciliation ticks; garbage warns and
    degrades to the default (the env_float contract)."""
    return env_float(FLEET_PROBE_INTERVAL_S_ENV_VAR, FLEET_PROBE_INTERVAL_S, minimum=0.05)


def fleet_probation_probes() -> int:
    """Consecutive probe successes a returning host needs before going live."""
    return env_int(FLEET_PROBATION_PROBES_ENV_VAR, FLEET_PROBATION_PROBES, minimum=1)


def fleet_dead_after_probes() -> int:
    """Consecutive probe failures that move a suspect host to dead."""
    return env_int(FLEET_DEAD_AFTER_PROBES_ENV_VAR, FLEET_DEAD_AFTER_PROBES, minimum=1)


def fleet_lease_ttl_s() -> float:
    """Coordinator heartbeat-lease TTL in seconds."""
    return env_float(FLEET_LEASE_TTL_S_ENV_VAR, FLEET_LEASE_TTL_S, minimum=0.1)


def fleet_host_roles() -> "dict[str, int]":
    """The per-HOST role census for the fleet coordinator, parsed with the
    same grammar (and warn-and-degrade contract) as :func:`serve_replica_roles`;
    ``{}`` = every host mixed."""
    raw = os.environ.get(FLEET_HOST_ROLES_ENV_VAR)
    if raw is None or not raw.strip():
        return {}
    try:
        return parse_replica_roles(raw)
    except ValueError as exc:
        logger.warning(
            f"ignoring {FLEET_HOST_ROLES_ENV_VAR}={raw!r} ({exc}); "
            "falling back to a symmetric (all-mixed) host fleet"
        )
        return {}


# --------------------------------------------------------------- observability
# Request-tracing / flight-recorder / profiler knobs (unionml_tpu/observability,
# docs/observability.md). Same export pattern as the admission knobs above: the
# serve CLI sets the env vars before the app module imports, and the serving
# app reads them at construction.

#: 1 = record a per-request timeline (spans at every lifecycle stage) into the
#: flight recorder; 0 = off (request ids still flow — tracing is the only part
#: with a cost, and it is strictly zero-allocation while off).
SERVE_TRACE_ENV_VAR = "UNIONML_TPU_TRACE"

#: completed request timelines the flight recorder retains (ring buffer).
SERVE_FLIGHT_RECORDER_ENV_VAR = "UNIONML_TPU_FLIGHT_RECORDER_SIZE"
SERVE_FLIGHT_RECORDER_SIZE = 256

#: log line format: "text" (classic prefix) or "json" (structured lines
#: carrying the request id — see _logging.JsonFormatter).
SERVE_LOG_FORMAT_ENV_VAR = "UNIONML_TPU_LOG_FORMAT"

#: directory ``POST /debug/profile`` writes jax.profiler traces into; unset
#: disables the endpoint (it answers 400 with a pointer to the flag).
SERVE_PROFILE_DIR_ENV_VAR = "UNIONML_TPU_PROFILE_DIR"

#: ceiling on one on-demand profile capture (ms): a runaway duration request
#: must not leave the profiler running for hours.
SERVE_PROFILE_MAX_MS = 60_000.0

#: directory ``serve --record-traffic`` captures live traffic traces into
#: (workloads/traces.py TraceRecorder); unset = capture off.
SERVE_RECORD_TRAFFIC_ENV_VAR = "UNIONML_TPU_RECORD_TRAFFIC"

#: record SHA-256 digests + lengths instead of prompt token ids (privacy
#: posture for traces that leave the machine); 0/unset = literal ids.
SERVE_RECORD_TRAFFIC_HASH_ENV_VAR = "UNIONML_TPU_RECORD_TRAFFIC_HASH"

# ------------------------------------------------------------ SLOs / fleet health
# Declarative serving SLO targets (observability/slo.py, docs/observability.md
# "SLOs and fleet health"). Same early-export contract as the knobs above: the
# serve CLI sets the env vars before the app module imports, and every
# continuous engine's SLO tracker reads them at construction. 0/unset disarms
# an objective — an engine with no targets evaluates as healthy.

#: TTFT p95 target in ms over the burn-rate windows (0 = disarmed).
SERVE_SLO_TTFT_P95_MS_ENV_VAR = "UNIONML_TPU_SLO_TTFT_P95_MS"

#: TBT p99 target in ms (0 = disarmed).
SERVE_SLO_TBT_P99_MS_ENV_VAR = "UNIONML_TPU_SLO_TBT_P99_MS"

#: tolerated shed fraction of arrivals, e.g. 0.01 (0 = disarmed).
SERVE_SLO_SHED_RATIO_ENV_VAR = "UNIONML_TPU_SLO_SHED_RATIO"

#: fast burn-rate window (seconds): the paging window — a breach needs the
#: fast window over target, so a long-gone incident cannot page.
SERVE_SLO_FAST_WINDOW_S_ENV_VAR = "UNIONML_TPU_SLO_FAST_WINDOW_S"
SERVE_SLO_FAST_WINDOW_S = 60.0

#: slow burn-rate window (seconds): the trend confirmation — breach requires
#: BOTH windows over target; one alone is warn.
SERVE_SLO_SLOW_WINDOW_S_ENV_VAR = "UNIONML_TPU_SLO_SLOW_WINDOW_S"
SERVE_SLO_SLOW_WINDOW_S = 600.0

#: samples (or arrivals, for the shed ratio) a window needs before it can
#: breach: an idle engine is healthy, not failing.
SERVE_SLO_MIN_SAMPLES_ENV_VAR = "UNIONML_TPU_SLO_MIN_SAMPLES"
SERVE_SLO_MIN_SAMPLES = 3


def env_int(name: str, default: int, *, minimum: "int | None" = None) -> int:
    """Parse an integer env var, tolerating garbage: unset/empty -> ``default``,
    a non-integer value warns and falls back to ``default`` instead of raising
    ``ValueError`` at whatever moment the knob happens to be read (for serve
    knobs that is import/export time in ``cli.py serve`` — a typo'd deployment
    env must degrade to the default, not take the service down). ``minimum``
    clamps the parsed value (e.g. a negative replica count means 0)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        value = int(raw.strip())
    except ValueError:
        logger.warning(f"ignoring non-integer {name}={raw!r}; falling back to {default}")
        return default
    if minimum is not None and value < minimum:
        logger.warning(f"clamping {name}={value} to the minimum {minimum}")
        return minimum
    return value


def env_float(name: str, default: float, *, minimum: "float | None" = None) -> float:
    """:func:`env_int` for float-valued knobs (same warn-and-fall-back
    contract; a garbage value must never crash the reader)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        value = float(raw.strip())
    except ValueError:
        logger.warning(f"ignoring non-numeric {name}={raw!r}; falling back to {default}")
        return default
    if minimum is not None and value < minimum:
        logger.warning(f"clamping {name}={value} to the minimum {minimum}")
        return minimum
    return value


def env_choice(name: str, choices: "tuple[str, ...]", what: str) -> "str | None":
    """Parse a choice-valued env var with the :func:`env_int` tolerance
    contract: unset/empty/"none"/"off"/"0" mean None (the knob's off state), a
    listed choice is returned normalized, and anything else warns and falls
    back to None instead of raising at whatever moment the knob happens to be
    read (for serve knobs that is app-import time — a typo'd deployment env
    must degrade to full precision, not take the service down). ``what`` names
    the knob in the warning (e.g. "quantize mode"), mirroring the ValueError
    text the explicit API raises for the same mistake."""
    raw = os.environ.get(name)
    if raw is None:
        return None
    value = raw.strip().lower()
    if value in ("", "none", "off", "0"):
        return None
    if value in choices:
        return value
    logger.warning(
        f"ignoring {name}={raw!r}: unsupported {what}; expected one of "
        f"{choices + ('none',)} — falling back to full precision"
    )
    return None


#: env values that mean "on, default location" / "off" for path-or-flag knobs
_TRUTHY_FLAGS = ("1", "true", "yes", "on")
_FALSY_FLAGS = ("", "0", "false", "no", "off")


def _env_path_flag(name: str, default_dir: str) -> "str | None":
    """Parse a path-or-flag env var: off-flags (and unset) mean None, truthy
    flags mean ``default_dir``, anything else is the path itself. Whether the
    path is *usable* is the consumer's concern — ProgramStore/compile_cache
    warn and degrade on an unwritable directory (the serve-export contract:
    a garbage value must never crash serve at app-import time)."""
    raw = os.environ.get(name)
    if raw is None:
        return None
    value = raw.strip()
    if value.lower() in _FALSY_FLAGS:
        return None
    if value.lower() in _TRUTHY_FLAGS:
        return default_dir
    return value


def serve_compile_cache() -> "str | None":
    """The persistent XLA compilation cache directory
    (``UNIONML_TPU_COMPILE_CACHE``); None = off. The package-import hook in
    compile_cache.py is the normal consumer — this reader exists for code
    that wants the resolved path (the cold-start bench, diagnostics)."""
    return _env_path_flag(SERVE_COMPILE_CACHE_ENV_VAR, "~/.cache/unionml_tpu/xla")


def serve_aot_preload() -> "str | None":
    """The AOT program store directory (``UNIONML_TPU_AOT_PRELOAD``); None =
    off. Read at engine/Generator construction, after the CLI's early export
    — same contract as :func:`serve_admit_chunk`. An unusable directory warns
    and degrades at ProgramStore construction, never at read time."""
    return _env_path_flag(SERVE_AOT_PRELOAD_ENV_VAR, "~/.cache/unionml_tpu/aot")


def serve_quantize() -> "str | None":
    """The serve-time weight-quantization mode ("int8" or None); read at
    Generator construction, after the CLI's early export — same contract as
    :func:`serve_dp_replicas`. Garbage (``UNIONML_TPU_QUANTIZE=fp4``) warns
    and falls back to None rather than crashing serve at app-import time."""
    return env_choice(SERVE_QUANTIZE_ENV_VAR, ("int8",), "quantize mode")


def serve_kv_cache_dtype() -> "str | None":
    """The serve-time KV-cache storage dtype ("int8" or None = compute dtype);
    read at Generator construction, same contract as :func:`serve_quantize`."""
    return env_choice(SERVE_KV_CACHE_DTYPE_ENV_VAR, ("int8",), "kv_cache_dtype")


def serve_tenant_config() -> "str | None":
    """Path to the serve-time tenants.json (``UNIONML_TPU_TENANT_CONFIG``);
    None = unset. Existence/validity is the registry's concern — it warns and
    degrades on a bad file (the serve-export contract), so a stale path in a
    fleet-wide env never crashes serve at app-import time."""
    raw = os.environ.get(SERVE_TENANT_CONFIG_ENV_VAR)
    if raw is None or not raw.strip():
        return None
    return raw.strip()


def serve_default_tenant_rate() -> float:
    """Requests/s bucket rate for identified-but-unconfigured tenants; 0 =
    unlimited (and, with no config file either, tenancy entirely off). Same
    warn-and-fall-back contract as every serve reader."""
    return env_float(SERVE_DEFAULT_TENANT_RATE_ENV_VAR, 0.0, minimum=0.0)


def serve_dp_replicas() -> int:
    """The serve-time data-parallel replica override; 0 = unset (derive the
    replica count from the mesh's data/fsdp axes). Read at call time, not
    import time — engine construction usually happens long after this module
    imports, and the CLI sets the env var in between. Garbage values
    (``UNIONML_TPU_DP_REPLICAS=abc``) warn and fall back to 0 rather than
    crashing ``serve`` at app-import time."""
    return env_int(SERVE_DP_REPLICAS_ENV_VAR, 0, minimum=0)


def serve_admit_chunk() -> int:
    """Serve-time admission prefill chunk width; 0 = unset. Read at engine
    construction (after the CLI export), same contract as
    :func:`serve_dp_replicas`."""
    return env_int(SERVE_ADMIT_CHUNK_ENV_VAR, 0, minimum=0)


def serve_prefill_budget() -> int:
    """Serve-time per-iteration prefill-token budget; 0 = unset (one chunk)."""
    return env_int(SERVE_PREFILL_BUDGET_ENV_VAR, 0, minimum=0)


def serve_max_admissions() -> int:
    """Serve-time cap on concurrent partially-prefilled admissions; 0 = unset."""
    return env_int(SERVE_MAX_ADMISSIONS_ENV_VAR, 0, minimum=0)


def serve_prefix_cache() -> bool:
    """Whether the serve-time radix prefix cache is on
    (``UNIONML_TPU_PREFIX_CACHE=1``); read at engine construction, after the
    CLI's early export, same contract as :func:`serve_admit_chunk`."""
    return env_int(SERVE_PREFIX_CACHE_ENV_VAR, 0, minimum=0) > 0


#: roles a replica may carry (serving/replicas.py); "mixed" is today's
#: prefill-and-decode-in-one behavior and the default for every replica.
REPLICA_ROLES = ("prefill", "decode", "mixed")


def parse_replica_roles(raw: str) -> "dict[str, int]":
    """Parse a ``prefill=1,decode=3`` role spec into ``{role: count}``.
    Raises ``ValueError`` naming the offending entry — the CLI surfaces it as
    a usage error; the env reader below degrades instead."""
    out: "dict[str, int]" = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        role, sep, count = entry.partition("=")
        role = role.strip().lower()
        if not sep or role not in REPLICA_ROLES:
            raise ValueError(
                f"bad replica-role entry {entry!r}; expected role=count with role in "
                f"{REPLICA_ROLES} (e.g. 'prefill=1,decode=3')"
            )
        try:
            n = int(count.strip())
        except ValueError:
            raise ValueError(f"bad replica-role count in {entry!r}; expected an integer")
        if n < 0:
            raise ValueError(f"replica-role count must be >= 0 in {entry!r}")
        out[role] = out.get(role, 0) + n
    return {role: n for role, n in out.items() if n > 0}


def serve_replica_roles() -> "dict[str, int]":
    """The serve-time ``--replica-roles`` export parsed to ``{role: count}``;
    ``{}`` = unset (a symmetric, all-mixed fleet). Read at ReplicaSet
    construction, after the CLI's early export — garbage warns and falls back
    to symmetric rather than crashing serve at app-import time."""
    raw = os.environ.get(SERVE_REPLICA_ROLES_ENV_VAR)
    if raw is None or not raw.strip():
        return {}
    try:
        return parse_replica_roles(raw)
    except ValueError as exc:
        logger.warning(
            f"ignoring {SERVE_REPLICA_ROLES_ENV_VAR}={raw!r} ({exc}); "
            "falling back to a symmetric (all-mixed) fleet"
        )
        return {}


def serve_prefill_threshold() -> int:
    """Prompt-length threshold (tokens) for routing to prefill-role replicas;
    0 = every admission disaggregates once roles are configured."""
    return env_int(SERVE_PREFILL_THRESHOLD_ENV_VAR, 0, minimum=0)


def serve_autoscale_high() -> float:
    """Autoscaler high watermark on per-replica load; 0.0 = autoscaler off."""
    return env_float(SERVE_AUTOSCALE_HIGH_ENV_VAR, 0.0, minimum=0.0)


def serve_autoscale_low() -> float:
    """Autoscaler low watermark; 0.0 = never scale down."""
    return env_float(SERVE_AUTOSCALE_LOW_ENV_VAR, 0.0, minimum=0.0)


def serve_autoscale_interval_s() -> float:
    """Seconds between autoscaler evaluations."""
    return env_float(
        SERVE_AUTOSCALE_INTERVAL_S_ENV_VAR, SERVE_AUTOSCALE_INTERVAL_S, minimum=0.05
    )


def serve_min_replicas() -> int:
    """Fleet-size floor for the autoscaler."""
    return env_int(SERVE_MIN_REPLICAS_ENV_VAR, 1, minimum=1)


def serve_max_replicas() -> int:
    """Fleet-size ceiling for the autoscaler; 0 = spare-capacity bound."""
    return env_int(SERVE_MAX_REPLICAS_ENV_VAR, 0, minimum=0)


def serve_trace() -> bool:
    """Whether serve-time request tracing is on (``UNIONML_TPU_TRACE=1``);
    read at app construction, after the CLI's early export."""
    return env_int(SERVE_TRACE_ENV_VAR, 0, minimum=0) > 0


def serve_flight_recorder_size() -> int:
    """Completed request timelines the flight recorder retains; garbage or
    sub-1 values degrade to the default (the recorder requires >= 1)."""
    return env_int(SERVE_FLIGHT_RECORDER_ENV_VAR, SERVE_FLIGHT_RECORDER_SIZE, minimum=1)


def serve_profile_dir() -> "str | None":
    """Directory for on-demand ``POST /debug/profile`` captures; None = the
    endpoint is disabled."""
    raw = os.environ.get(SERVE_PROFILE_DIR_ENV_VAR)
    return raw.strip() or None if raw is not None else None


def serve_record_traffic() -> "str | None":
    """Directory live traffic is captured into as replayable traces
    (``serve --record-traffic``, workloads/traces.py); None = capture off.
    Read at app construction, after the CLI's early export — an unusable
    directory degrades at TraceRecorder construction (warn, capture off),
    never at read time."""
    raw = os.environ.get(SERVE_RECORD_TRAFFIC_ENV_VAR)
    return raw.strip() or None if raw is not None else None


def serve_record_traffic_hash() -> bool:
    """Whether captured traces carry prompt digests instead of token ids."""
    return env_int(SERVE_RECORD_TRAFFIC_HASH_ENV_VAR, 0, minimum=0) > 0


def serve_slo_ttft_p95_ms() -> float:
    """Serve-time TTFT p95 SLO target in ms; 0.0 = disarmed. Read at engine
    construction (after the CLI's early export), same contract as
    :func:`serve_admit_chunk` — garbage warns and falls back, never crashes
    serve at app-import time."""
    return env_float(SERVE_SLO_TTFT_P95_MS_ENV_VAR, 0.0, minimum=0.0)


def serve_slo_tbt_p99_ms() -> float:
    """Serve-time TBT p99 SLO target in ms; 0.0 = disarmed."""
    return env_float(SERVE_SLO_TBT_P99_MS_ENV_VAR, 0.0, minimum=0.0)


def serve_slo_shed_ratio() -> float:
    """Serve-time shed-ratio SLO target (fraction of arrivals); 0.0 = disarmed."""
    return env_float(SERVE_SLO_SHED_RATIO_ENV_VAR, 0.0, minimum=0.0)


def serve_slo_fast_window_s() -> float:
    """Fast burn-rate window in seconds (the paging window)."""
    return env_float(SERVE_SLO_FAST_WINDOW_S_ENV_VAR, SERVE_SLO_FAST_WINDOW_S, minimum=1.0)


def serve_slo_slow_window_s() -> float:
    """Slow burn-rate window in seconds (the trend-confirmation window)."""
    return env_float(SERVE_SLO_SLOW_WINDOW_S_ENV_VAR, SERVE_SLO_SLOW_WINDOW_S, minimum=1.0)


def serve_slo_min_samples() -> int:
    """Samples a window needs before it can breach (idle engines stay healthy)."""
    return env_int(SERVE_SLO_MIN_SAMPLES_ENV_VAR, SERVE_SLO_MIN_SAMPLES, minimum=1)
