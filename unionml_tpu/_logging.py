"""Package logger.

Parity: reference unionml/_logging.py:3-7 (stream logger with a ``[unionml]``
prefix). Extended for serving observability (docs/observability.md):

- ``UNIONML_TPU_LOGLEVEL`` is validated — a garbage value (``=garbage``) warns
  and falls back to INFO instead of raising ``ValueError`` at import time,
  before any app code has run (the same warn-and-fall-back contract as
  :func:`unionml_tpu.defaults.env_int`);
- ``UNIONML_TPU_LOG_FORMAT=json`` (or :func:`set_log_format` — the ``serve
  --log-format json`` flag lands there) switches every line to one JSON
  object carrying the active request id from
  :mod:`unionml_tpu.observability.trace`, so access-log lines correlate with
  ``/debug/requests`` timelines by ``request_id``.
"""

import json
import logging
import os

_VALID_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL", "NOTSET", "WARN", "FATAL")

_TEXT_FORMAT = "[unionml-tpu] %(asctime)s %(levelname)s %(message)s"


class JsonFormatter(logging.Formatter):
    """One JSON object per line: timestamp, level, message, and — when a
    request is being handled — its ``request_id``, the correlation key into
    the flight recorder's timelines."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exc_info"] = self.formatException(record.exc_info)
        try:
            # lazy import: observability.trace imports nothing from here, but
            # keeping the edge out of module scope avoids any cycle risk and
            # keeps bare-logger users free of the serving stack
            from unionml_tpu.observability.trace import current_request_id

            request_id = current_request_id()
            if request_id is not None:
                out["request_id"] = request_id
        except Exception:  # pragma: no cover - never fail a log line
            pass
        return json.dumps(out, default=str)


def _resolve_level() -> "tuple[str, str | None]":
    """``(level, warning)`` from the env: an unknown name degrades to INFO with
    a warning emitted AFTER the handler is attached (the logger must exist
    before it can complain about its own configuration)."""
    raw = os.environ.get("UNIONML_TPU_LOGLEVEL", "INFO").strip().upper()
    if raw in _VALID_LEVELS:
        return raw, None
    return "INFO", f"ignoring invalid UNIONML_TPU_LOGLEVEL={raw!r}; falling back to INFO"


def set_log_format(fmt: str) -> None:
    """Switch the package handler's formatter: ``"json"`` for structured
    lines (request-id correlation), anything else for the classic text
    prefix. The ``serve --log-format`` flag calls this."""
    formatter: logging.Formatter = (
        JsonFormatter() if str(fmt).strip().lower() == "json" else logging.Formatter(_TEXT_FORMAT)
    )
    for handler in logger.handlers:
        handler.setFormatter(formatter)


logger = logging.getLogger("unionml_tpu")
_level, _level_warning = _resolve_level()
logger.setLevel(_level)
if not logger.handlers:  # re-imports (importlib.reload) must not stack handlers
    _handler = logging.StreamHandler()
    logger.addHandler(_handler)
logger.propagate = False
set_log_format(os.environ.get("UNIONML_TPU_LOG_FORMAT", "text"))
if _level_warning:
    logger.warning(_level_warning)
