"""Package logger.

Parity: reference unionml/_logging.py:3-7 (stream logger with a ``[unionml]`` prefix).
"""

import logging
import os

logger = logging.getLogger("unionml_tpu")
logger.setLevel(os.environ.get("UNIONML_TPU_LOGLEVEL", "INFO"))
_handler = logging.StreamHandler()
_handler.setFormatter(logging.Formatter("[unionml-tpu] %(asctime)s %(levelname)s %(message)s"))
logger.addHandler(_handler)
logger.propagate = False
