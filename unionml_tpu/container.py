"""Container images for app deployment.

Parity surface: the reference builds a per-app docker image at deploy time and
tags it ``{registry}/{image_name}:{model-name}-{version}``
(unionml/remote.py:60-108, root Dockerfile:1); ``patch`` deploys skip image
work (model.py:700-701). Here the analog targets TPU-VM/GKE topologies: the
image is built FROM the deployed source bundle (not the working tree), so the
image content is exactly what the store records for the app version, and the
default Dockerfile installs the TPU jax wheel and enters through
``unionml_tpu.job_runner`` — one container per slice host.

The docker invocation is a plain CLI shell-out with an injectable ``runner``,
the same seam the TPU-VM launcher uses for gcloud — tests drive the real code
path through a shim ``docker`` binary on PATH
(tests/integration/test_container.py).
"""

from __future__ import annotations

import subprocess
from pathlib import Path
from typing import Callable, Optional

from unionml_tpu._logging import logger

__all__ = ["DEFAULT_DOCKERFILE", "build_image", "ensure_dockerfile", "image_fqn", "push_image"]

#: TPU-VM serving/training base image: the app bundle is the build context, so
#: COPY ships exactly the deployed source. Swap the jax extra for your
#: accelerator (``jax[tpu]`` pulls libtpu from the Google releases index).
DEFAULT_DOCKERFILE = """\
FROM python:3.12-slim

WORKDIR /app
ENV PYTHONPATH=/app
ENV PIP_NO_CACHE_DIR=1

RUN pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \\
    && pip install unionml-tpu

# the deployed source bundle is the build context
COPY . /app

# one container per slice host; the backend supplies the jax.distributed env
# (UNIONML_TPU_COORDINATOR / .._NUM_PROCESSES / .._PROCESS_ID) at run time
ENTRYPOINT ["python", "-m", "unionml_tpu.job_runner"]
"""

Runner = Callable[..., "subprocess.CompletedProcess"]


def image_fqn(
    model_name: str, app_version: str, registry: Optional[str] = None, image_name: Optional[str] = None
) -> str:
    """``{registry}/{image_name}:{model-name}-{version}`` (reference remote.py:60-66)."""
    name = image_name or "unionml-tpu"
    uri = f"{registry}/{name}" if registry else name
    return f"{uri}:{model_name.replace('_', '-')}-{app_version}"


def ensure_dockerfile(bundle_dir: Path, dockerfile: str = "Dockerfile") -> Path:
    """Return the bundle's Dockerfile path, writing :data:`DEFAULT_DOCKERFILE`
    if the app did not ship one (the reference requires a checked-in Dockerfile;
    a generated default keeps simple apps zero-config)."""
    path = Path(bundle_dir) / dockerfile
    if not path.exists():
        logger.info(f"app has no {dockerfile}; writing the default TPU-VM Dockerfile")
        path.write_text(DEFAULT_DOCKERFILE)
    return path


def build_image(
    bundle_dir: Path, fqn: str, dockerfile: str = "Dockerfile", runner: Optional[Runner] = None
) -> None:
    """``docker build`` the app bundle into ``fqn`` (reference remote.py:91-105)."""
    run = runner or subprocess.run
    dockerfile_path = ensure_dockerfile(Path(bundle_dir), dockerfile)
    command = [
        "docker", "build", str(bundle_dir), "--file", str(dockerfile_path), "--tag", fqn,
    ]
    logger.info(f"building image: {' '.join(command)}")
    proc = run(command)
    if getattr(proc, "returncode", 0) != 0:
        raise RuntimeError(f"docker build of {fqn} failed with rc={proc.returncode}")


def push_image(fqn: str, runner: Optional[Runner] = None) -> None:
    """``docker push`` (reference remote.py:106-108)."""
    run = runner or subprocess.run
    command = ["docker", "push", fqn]
    logger.info(f"pushing image: {' '.join(command)}")
    proc = run(command)
    if getattr(proc, "returncode", 0) != 0:
        raise RuntimeError(f"docker push of {fqn} failed with rc={proc.returncode}")
