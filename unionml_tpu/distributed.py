"""One jax.distributed bootstrap shared by train and serve.

Extracted from ``job_runner._maybe_init_distributed`` (which now delegates
here) so the serving fleet's worker processes (serving/cluster.py) join a
multi-process JAX runtime through exactly the code path the training
watchdog ring already pins: ``UNIONML_TPU_COORDINATOR`` names the rendezvous,
``UNIONML_TPU_NUM_PROCESSES``/``UNIONML_TPU_PROCESS_ID`` place this process,
and with the env unset every helper degrades to single-process no-ops — the
same code runs unchanged on one host.

On top of the bootstrap sit the small cross-host agreement primitives the
fleet coordinator needs (SNIPPETS.md's T5X ``multihost_utils`` shape):
:func:`barrier` fences every process at a named point, :func:`agree`
broadcasts process 0's JSON-able config so all hosts provably build the same
fleet, and :func:`allgather_ints` exchanges one small integer per process
(the control-plane port exchange). All three are collectives — EVERY process
of the runtime must call them, and none may be called while holding a lock
(tpu-lint TPU013: one stalled host would deadlock the whole fleet).
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.defaults import (
    distributed_coordinator,
    distributed_num_processes,
    distributed_process_id,
)

__all__ = [
    "agree",
    "allgather_ints",
    "barrier",
    "is_initialized",
    "maybe_initialize",
    "process_count",
    "process_index",
]

#: set by :func:`maybe_initialize` so repeated calls (job_runner then an app
#: module that also bootstraps) are idempotent instead of a jax RuntimeError
_initialized = False


def is_initialized() -> bool:
    """Whether THIS module initialized the jax.distributed runtime."""
    return _initialized


def maybe_initialize() -> bool:
    """Join the jax.distributed runtime named by the env, if any.

    Returns True when this process is now part of a multi-process runtime
    (idempotently: a second call is a no-op), False when the env names no
    coordinator — the single-process mode every caller must tolerate. Reads
    the knobs through the defaults.py warn-and-degrade readers, so a typo'd
    deployment env degrades to single-process instead of crashing the
    bootstrap."""
    global _initialized
    coordinator = distributed_coordinator()
    if not coordinator:
        return False
    if _initialized:
        return True
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # emulated multi-host lane: a TPU plugin on the path would win over the
        # env var, so pin the platform before the backend initializes
        jax.config.update("jax_platforms", "cpu")
        try:
            # CROSS-PROCESS computations on the CPU backend need the gloo
            # collectives implementation picked before the backend forms —
            # without it every multiprocess dispatch (multihost_utils
            # broadcasts included) fails with "Multiprocess computations
            # aren't implemented on the CPU backend"
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older/newer jax without the knob: leave the default
            pass
    num_processes = distributed_num_processes()
    process_id = distributed_process_id()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    # the definitive signal that the slice formed: this process sees every
    # device of every peer (watchdog tests assert on this line)
    logger.info(
        f"joined jax.distributed runtime: process {process_id}/{num_processes}, "
        f"global devices {jax.device_count()} ({jax.local_device_count()} local)"
    )
    return True


def process_index() -> int:
    """This process's index: jax's own once a runtime exists, else the env
    reader (so a worker can self-identify before/without initializing)."""
    if _initialized:
        import jax

        return int(jax.process_index())
    return distributed_process_id()


def process_count() -> int:
    """Total processes in the runtime (1 single-process)."""
    if _initialized:
        import jax

        return int(jax.process_count())
    return distributed_num_processes()


def barrier(name: str) -> None:
    """Fence every process of the runtime at a named sync point (a no-op
    single-process). A COLLECTIVE: never call it while holding a lock —
    a peer stuck elsewhere turns the lock into a fleet-wide deadlock
    (tpu-lint TPU013)."""
    if not _initialized:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def agree(obj: Any) -> Any:
    """Cross-host agreement on a small JSON-able value: every process returns
    PROCESS 0's ``obj`` — the fleet-config handshake (engine knobs, scale
    transitions) that guarantees knob-identical engines on every host.
    Single-process: returns ``obj`` unchanged. A COLLECTIVE (two
    ``broadcast_one_to_all`` rounds: length, then padded payload) — every
    process must call it, and never under a lock (TPU013)."""
    if not _initialized or process_count() == 1:
        return obj
    from jax.experimental import multihost_utils

    payload = json.dumps(obj, sort_keys=True).encode() if process_index() == 0 else b""
    length = int(
        multihost_utils.broadcast_one_to_all(np.int32(len(payload)))
    )
    # byte values ride as int32: broadcast_one_to_all widens small dtypes in
    # flight, so an int32 buffer round-trips exactly on every jax version
    buf = np.zeros((max(length, 1),), np.int32)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return json.loads(bytes(buf[:length].astype(np.uint8)).decode())


def allgather_ints(value: int) -> "List[int]":
    """Exchange one small integer per process (index order) — the fleet's
    control-plane port exchange. Single-process: ``[value]``. A COLLECTIVE:
    same never-under-a-lock contract as :func:`barrier` (TPU013)."""
    if not _initialized or process_count() == 1:
        return [int(value)]
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray([int(value)], np.int64))
    return [int(v) for v in np.asarray(gathered).ravel()]
