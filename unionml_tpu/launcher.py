"""Pluggable slice launchers: how an execution's worker processes come to exist.

The reference schedules work by handing a registered workflow to FlyteRemote,
which turns it into k8s pods (unionml/remote.py:111-147, model.py:732-796). Here
the equivalent seam is the :class:`Launcher` interface: the backend builds one
``job_runner`` command per worker (plus the jax.distributed coordinator env) and
a launcher decides where those commands run.

Two implementations ship:

- :class:`LocalProcessLauncher` — ``subprocess.Popen`` per worker on this host
  (the default; also the in-tree multi-host analog, N processes joining one
  ``jax.distributed`` runtime).
- :class:`TPUVMLauncher` — provisions a TPU slice for the manifest's
  ``accelerator`` (e.g. ``"v5e-8"``) and runs one worker per slice host through
  a ``gcloud compute tpus tpu-vm ssh``-shaped transport. The provisioner and
  transport are injectable, so tests (and alternative control planes — GKE,
  QueuedResources REST) swap in their own without touching the backend.

Every launcher returns process-like handles (``poll() / returncode / kill() /
wait()``) — the watchdog in :meth:`unionml_tpu.remote.Backend.wait` drives
failure detection and resubmission purely through that contract.
"""

from __future__ import annotations

import dataclasses
import subprocess
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

from unionml_tpu._logging import logger

__all__ = [
    "ContainerLauncher",
    "LaunchSpec",
    "Launcher",
    "LocalProcessLauncher",
    "TPUVMLauncher",
    "parse_accelerator",
    "slice_hosts",
]


@dataclasses.dataclass
class LaunchSpec:
    """Everything a launcher needs to start one execution's workers.

    ``worker_envs[i]`` already carries the per-worker jax.distributed variables
    (``UNIONML_TPU_COORDINATOR`` / ``.._NUM_PROCESSES`` / ``.._PROCESS_ID``) and
    the bundle-first ``PYTHONPATH``.
    """

    command: List[str]  # the job_runner invocation, identical on every worker
    worker_envs: List[Dict[str, str]]  # one env per worker, index = process id
    log_paths: List[Path]  # one log file per worker
    log_mode: str  # "w" first attempt, "a" on resubmit
    execution_path: str
    accelerator: Optional[str] = None
    #: 0-based relaunch counter (the watchdog's resubmit increments it) — lets
    #: launchers mint per-attempt resource names (container names must be fresh:
    #: a killed attempt's container lingers until the daemon reaps it)
    attempt: int = 0
    #: the app version's container image (deploy manifest's ``image``), when a
    #: registry was configured at deploy — what :class:`ContainerLauncher` runs
    image: Optional[str] = None
    #: the backend store root — container/remote launchers mount or sync it so
    #: the execution directory is visible inside the worker at the same path
    store_root: Optional[str] = None

    @property
    def n_workers(self) -> int:
        return len(self.worker_envs)


class Launcher:
    """Interface: turn a :class:`LaunchSpec` into live worker handles."""

    def launch(self, spec: LaunchSpec) -> List[Any]:  # pragma: no cover - interface
        raise NotImplementedError


class LocalProcessLauncher(Launcher):
    """Default launcher: one local subprocess per worker."""

    def launch(self, spec: LaunchSpec) -> List[Any]:
        handles: List[Any] = []
        for env, log_path in zip(spec.worker_envs, spec.log_paths):
            with open(log_path, spec.log_mode) as log_file:
                handles.append(
                    subprocess.Popen(spec.command, env=env, stdout=log_file, stderr=subprocess.STDOUT)
                )
        return handles


class ContainerLauncher(Launcher):
    """Run each worker as a container from the app's deployed image.

    This closes the reference's image-is-the-runtime contract
    (/root/reference/unionml/remote.py:91-108 builds+pushes, model.py:696 pins
    ``FLYTE_INTERNAL_IMAGE``, the cluster runs it): the image built at deploy
    (:mod:`unionml_tpu.container`, entrypoint ``unionml_tpu.job_runner``) is the
    execution vehicle, not just an artifact. Per worker::

        docker run --rm --network host \\
            -v <store_root>:<store_root> \\
            -e UNIONML_TPU_... -e JAX_... -e PYTHONPATH=... \\
            <manifest image> <execution_path>

    The store root is bind-mounted at the SAME path so the execution directory
    (spec/status/outputs) and the bundle are visible inside the container where
    the host-side backend expects them; the jax.distributed coordinator env
    rides ``--network host``, so multi-worker containers join one runtime
    exactly like local processes. The handle is the local ``docker run``
    process — the backend watchdog sees container death as docker exit, and the
    same shim seam as the gcloud launcher drives the real code path in tests
    (tests/integration/test_container.py).

    :param image: override the manifest image (e.g. a locally built tag); by
        default the :class:`LaunchSpec`'s ``image`` — the deploy manifest's —
        is required.
    :param docker_args: extra ``docker run`` arguments, e.g.
        ``("--privileged", "--device=/dev/accel0")`` for TPU-VM device access.
    """

    def __init__(self, *, image: Optional[str] = None, docker_args: Sequence[str] = ()):
        self.image = image
        self.docker_args = list(docker_args)

    def launch(self, spec: LaunchSpec) -> List[Any]:
        image = self.image or spec.image
        if not image:
            raise ValueError(
                "ContainerLauncher needs an image: deploy with a registry configured "
                "(the manifest then records the built image) or pass ContainerLauncher(image=...)"
            )
        exec_name = Path(spec.execution_path).name
        handles: List[Any] = []
        for worker, (env, log_path) in enumerate(zip(spec.worker_envs, spec.log_paths)):
            # per-ATTEMPT name: a watchdog-killed attempt's container lingers
            # until the daemon reaps it, and a name reuse would fail every retry
            # with a daemon name conflict
            name = f"unionml-{exec_name}-a{spec.attempt}-w{worker}"
            command = ["docker", "run", "--rm", "--network", "host", "--name", name]
            if spec.store_root:
                command += ["-v", f"{spec.store_root}:{spec.store_root}"]
            for key, value in env.items():
                if key.startswith(("UNIONML_TPU_", "PYTHONPATH", "JAX_")):
                    command += ["-e", f"{key}={value}"]
            command += self.docker_args
            # the image's entrypoint is `python -m unionml_tpu.job_runner`; the
            # execution path is its argument
            command += [image, spec.execution_path]
            with open(log_path, spec.log_mode) as log_file:
                proc = subprocess.Popen(command, env=env, stdout=log_file, stderr=subprocess.STDOUT)
            handles.append(_ContainerHandle(proc, name))
        return handles


class _ContainerHandle:
    """Process-like handle for one containerized worker. ``poll``/``wait``/
    ``returncode`` proxy the local ``docker run`` client (container death IS
    client exit), but ``kill`` must target the CONTAINER — SIGKILL to the
    client is never proxied to the daemon-side process, and a worker that
    survived its own kill would keep mutating the bind-mounted execution dir
    while the resubmitted attempt writes the same files."""

    def __init__(self, proc: "subprocess.Popen", name: str):
        self._proc = proc
        self.container_name = name

    def poll(self):
        return self._proc.poll()

    def wait(self, timeout: Optional[float] = None):
        return self._proc.wait(timeout)

    @property
    def returncode(self):
        return self._proc.returncode

    def kill(self) -> None:
        result = subprocess.run(
            ["docker", "kill", self.container_name],
            check=False, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        if result.returncode != 0:
            # the daemon-side worker may still be running and mutating the
            # mounted execution dir — the one hazard this handle exists to
            # prevent; it must not fail silently
            logger.warning(
                f"docker kill {self.container_name} failed (rc={result.returncode}): "
                f"{result.stderr.decode(errors='replace').strip()}; the container may still be running"
            )
        self._proc.kill()


#: chips per host for each TPU generation prefix — the worker count for a slice is
#: ceil(chips / chips_per_host). Matches single-slice TPU-VM topology (a v5e host
#: exposes at most 8 chips; v4/v5p hosts expose 4).
_CHIPS_PER_HOST = {
    "v6e": 8,
    "v5e": 8,
    "v5litepod": 8,
    "v5p": 4,
    "v4": 4,
    "v3": 4,
    "v2": 4,
}


def parse_accelerator(accelerator: str) -> "tuple[str, int]":
    """``"v5e-16"`` -> ``("v5e", 16)`` (generation, CHIP count). The core-counted
    generations (v2-v4, v5p) are halved: ``v4-32`` is 32 TensorCores = 16 chips.
    The single accelerator-string parser — :func:`slice_hosts` and the GKE
    manifest emitter (:mod:`unionml_tpu.gke`) both resolve through it."""
    name, _, count_str = accelerator.rpartition("-")
    name = name.lower()
    try:
        count = int(count_str)
    except ValueError:
        raise ValueError(f"cannot parse accelerator {accelerator!r}; expected e.g. 'v5e-8'")
    if name not in _CHIPS_PER_HOST:
        raise ValueError(f"unknown TPU generation in accelerator {accelerator!r}")
    chips = count // 2 if name in ("v2", "v3", "v4", "v5p") else count  # core-counted gens
    return name, max(1, chips)


def slice_hosts(accelerator: str) -> int:
    """Number of hosts (worker processes) in an accelerator slice, e.g. ``v5e-8`` -> 1,
    ``v5e-16`` -> 2, ``v4-32`` -> 4 (v4 counts TensorCores: 32 cores = 16 chips)."""
    name, chips = parse_accelerator(accelerator)
    return max(1, -(-chips // _CHIPS_PER_HOST[name]))


class TPUVMLauncher(Launcher):
    """Launch workers onto a provisioned TPU slice, one per slice host.

    :param provisioner: ``(accelerator, execution_path) -> node_name``. Called at
        most once per execution — relaunches of the same execution (the watchdog's
        ``resubmit``) reuse the cached node instead of re-creating it. The default
        shells out a ``gcloud``-shaped create command. Tests inject a fake that
        records the request.
    :param transport: ``(node_name, worker_index, command, env, log_path, log_mode)
        -> handle``. The default wraps the command in ``gcloud compute tpus tpu-vm
        ssh --worker=<i>``; the returned handle is the local ssh process, so the
        backend watchdog sees worker death as ssh exit.

    The default transport assumes the store root and the Python environment are
    visible on the slice hosts at the same paths as on the submitting machine
    (the standard TPU-pod setup: an NFS-mounted store + a baked VM image). For
    any other topology, inject a transport that ships the bundle first (e.g.
    ``gcloud ... scp`` + a container image) — the backend only depends on the
    returned handles. Slice lifecycle is deliberately not tied to one execution:
    call :meth:`teardown` when done with a node.
    """

    def __init__(
        self,
        *,
        project: Optional[str] = None,
        zone: Optional[str] = None,
        version: str = "tpu-ubuntu2204-base",
        provisioner: Optional[Callable[[str, str], str]] = None,
        transport: Optional[Callable[..., Any]] = None,
        deprovisioner: Optional[Callable[[str], None]] = None,
    ):
        self.project = project
        self.zone = zone
        self.version = version
        self._provisioner = provisioner or self._gcloud_provision
        self._transport = transport or self._gcloud_ssh
        # injected provisioners own their nodes' lifecycle; only the default
        # gcloud provisioner pairs with the default gcloud delete
        self._deprovisioner = deprovisioner or (self._gcloud_delete if provisioner is None else (lambda node: None))
        self._nodes: Dict[str, str] = {}  # execution_path -> provisioned node

    # ---------------------------------------------------------------- defaults

    def _gcloud_args(self) -> List[str]:
        args: List[str] = []
        if self.project:
            args += ["--project", self.project]
        if self.zone:
            args += ["--zone", self.zone]
        return args

    def _gcloud_provision(self, accelerator: str, execution_path: str) -> str:
        node = f"unionml-{Path(execution_path).name}"
        command = [
            "gcloud", "compute", "tpus", "tpu-vm", "create", node,
            f"--accelerator-type={accelerator}",
            f"--version={self.version}",
            *self._gcloud_args(),
        ]
        logger.info(f"provisioning TPU slice: {' '.join(command)}")
        try:
            subprocess.run(command, check=True)
        except subprocess.CalledProcessError as exc:
            # a failed create can still leave a half-provisioned (billed!) node
            # behind; clean it up best-effort so the retry's create doesn't hit
            # "already exists" — then surface the original failure
            logger.warning(f"TPU slice create failed (rc={exc.returncode}); cleaning up {node}")
            try:
                self._gcloud_delete(node)
            except Exception as cleanup_exc:
                logger.warning(f"cleanup of partially created node {node} also failed: {cleanup_exc}")
            raise RuntimeError(
                f"provisioning TPU slice {node} ({accelerator}) failed with rc={exc.returncode}"
            ) from exc
        return node

    def _gcloud_ssh(
        self,
        node: str,
        worker: int,
        command: Sequence[str],
        env: Dict[str, str],
        log_path: Path,
        log_mode: str,
    ) -> Any:
        import shlex

        exports = " ".join(
            f"{k}={shlex.quote(v)}"
            for k, v in env.items()
            if k.startswith(("UNIONML_TPU_", "PYTHONPATH", "JAX_"))
        )
        remote_cmd = f"{exports} {' '.join(shlex.quote(c) for c in command)}"
        ssh = [
            "gcloud", "compute", "tpus", "tpu-vm", "ssh", node,
            f"--worker={worker}",
            *self._gcloud_args(),
            "--command", remote_cmd,
        ]
        log_file = open(log_path, log_mode)
        return subprocess.Popen(ssh, env=env, stdout=log_file, stderr=subprocess.STDOUT)

    # ---------------------------------------------------------------- interface

    def launch(self, spec: LaunchSpec) -> List[Any]:
        if not spec.accelerator:
            raise ValueError("TPUVMLauncher requires an accelerator in the backend config/manifest")
        expected = slice_hosts(spec.accelerator)
        if spec.n_workers != expected:
            logger.warning(
                f"accelerator {spec.accelerator} has {expected} hosts but n_workers="
                f"{spec.n_workers}; launching one worker per configured process"
            )
        # resubmits of the same execution reuse the provisioned slice — the
        # watchdog's retry path must not try to create an already-existing node
        node = self._nodes.get(spec.execution_path)
        if node is None:
            node = self._provisioner(spec.accelerator, spec.execution_path)
            self._nodes[spec.execution_path] = node
        return [
            self._transport(node, worker, spec.command, env, log_path, spec.log_mode)
            for worker, (env, log_path) in enumerate(zip(spec.worker_envs, spec.log_paths))
        ]

    def _gcloud_delete(self, node: str) -> None:
        command = ["gcloud", "compute", "tpus", "tpu-vm", "delete", node, "--quiet", *self._gcloud_args()]
        logger.info(f"tearing down TPU slice: {' '.join(command)}")
        proc = subprocess.run(command, check=False)
        if proc.returncode != 0:
            # a silently swallowed delete failure leaks a billed slice; raise so
            # teardown's caller knows the node still exists
            raise RuntimeError(f"deleting TPU slice {node} failed with rc={proc.returncode}")

    def teardown(self, execution_path: str) -> None:
        """Delete the slice provisioned for an execution (no-op if none/unknown).

        On deprovision failure the node stays registered under its execution, so
        a later :meth:`teardown` retry targets it again instead of leaking it."""
        node = self._nodes.pop(execution_path, None)
        if node is None:
            return
        try:
            self._deprovisioner(node)
        except Exception:
            self._nodes[execution_path] = node  # keep it addressable for a retry
            raise
