"""Weight-only int8 quantization for inference.

No reference counterpart (the reference has no inference engine, SURVEY.md §0);
this is a TPU-native serving optimization. Small-batch autoregressive decode is
HBM-bandwidth bound — every step streams the full parameter bytes once — so
storing weights as int8 with per-output-channel scales halves the bytes per step
and, on the roofline, doubles decode throughput. Accuracy: per-channel symmetric
int8 on transformer matmul weights is the standard lossless-in-practice setting
(GPTQ/AWQ quantize further, to 4-bit, from this baseline).

Mechanics: :func:`quantize_params` rewrites selected 2D+ leaves of a params
pytree into :class:`QuantizedTensor` (int8 values + f32 per-channel scale, a
registered pytree so it flows through jit/donation/sharding untouched);
:func:`dequantize_tree` maps back to the compute dtype *inside* the jitted
computation, where XLA fuses the ``convert + multiply`` into the consumer's
HLO — the int8 bytes are what crosses HBM.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedTensor", "quantize_array", "quantize_params", "dequantize", "dequantize_tree"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Symmetric per-channel int8 weight: ``w ≈ q * scale`` with ``q`` int8 and
    ``scale`` broadcast over the quantization axis (default: per output channel,
    i.e. per trailing-dim column)."""

    q: jax.Array  # int8, same shape as the original weight
    scale: jax.Array  # f32, shape = weight shape with the reduction axes set to 1

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


def quantize_array(w: Any, *, channel_axis: int = -1) -> QuantizedTensor:
    """Quantize one weight to int8 with symmetric per-channel scales.

    With the default trailing ``channel_axis``, only the contraction axis (the
    one just before the channels) is reduced — so a 2D ``[K, F]`` kernel gets
    ``[1, F]`` per-output-channel scales, and a stacked MoE expert kernel
    ``[E, K, F]`` gets ``[E, 1, F]`` per-(expert, channel) scales rather than
    one scale plane shared across experts (which would let one outlier expert
    crush the resolution of the others)."""
    w = jnp.asarray(w)
    channel = channel_axis % w.ndim
    if channel == w.ndim - 1 and w.ndim >= 2:
        axes: Tuple[int, ...] = (w.ndim - 2,)
    else:
        axes = tuple(i for i in range(w.ndim) if i != channel)
    abs_max = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(abs_max, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def dequantize(leaf: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse of :func:`quantize_array`; passes non-quantized leaves through."""
    if isinstance(leaf, QuantizedTensor):
        return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)
    return leaf


def _is_qt(x: Any) -> bool:
    return isinstance(x, QuantizedTensor)


def dequantize_tree(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Map :func:`dequantize` over a pytree (call *inside* jit so the convert
    fuses into consumers rather than materializing f32/bf16 copies in HBM)."""
    return jax.tree_util.tree_map(lambda x: dequantize(x, dtype), params, is_leaf=_is_qt)


#: default targets: large matmul kernels; embeddings stay unquantized (gather
#: reads one row per token — quantizing saves nothing and costs accuracy),
#: norms/biases/low-rank adapters are too small to matter, and MoE routers are
#: precision-sensitive (they run in f32 by design, moe.py)
_DEFAULT_INCLUDE = r"(kernel)$"
_DEFAULT_EXCLUDE = r"(embed|embedding|norm|scale|bias|lora_a|lora_b|router)"


def quantize_params(
    params: Any,
    *,
    include: str = _DEFAULT_INCLUDE,
    exclude: str = _DEFAULT_EXCLUDE,
    min_size: int = 1 << 16,
    channel_axis: int = -1,
) -> Any:
    """Quantize matching weight leaves of a params pytree to int8.

    A leaf is quantized when its path matches ``include``, does not match
    ``exclude``, has rank >= 2, and has at least ``min_size`` elements.
    """
    inc, exc = re.compile(include), re.compile(exclude)

    def path_str(path: Sequence[Any]) -> str:
        return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    def maybe_quantize(path, leaf):
        p = path_str(path)
        shape = getattr(leaf, "shape", ())
        if (
            inc.search(p)
            and not exc.search(p)
            and len(shape) >= 2
            and int(np.prod(shape)) >= min_size
        ):
            return quantize_array(leaf, channel_axis=channel_axis)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_quantize, params)
