"""TPU compute ops: attention kernels (XLA reference, pallas flash, ring/SP), int8 quant."""

from unionml_tpu.ops.attention import dot_product_attention, multihead_attention  # noqa: F401
from unionml_tpu.ops.int8_matmul import int8_matmul, quantized_matmul  # noqa: F401
from unionml_tpu.ops.quant import (  # noqa: F401
    QuantizedTensor,
    dequantize,
    dequantize_tree,
    quantize_array,
    quantize_params,
)
from unionml_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    sequence_sharded_attention,
    ulysses_attention,
)
