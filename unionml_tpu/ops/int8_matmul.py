"""Pallas int8 weight-only matmul: ``y = x @ (q * scale)`` with in-VMEM dequant.

Why a kernel: small-batch decode matmuls are HBM-bound on the weight bytes; this
kernel guarantees int8 is the only weight traffic — int8 tiles stream HBM->VMEM,
the int8->bf16 convert happens in VMEM, the MXU consumes bf16 tiles, and the
per-channel scales are applied once to the f32 accumulator at the end.

Grid ``(m_blocks, f_blocks, k_blocks)`` with the k (reduction) dim innermost and
sequential: the f32 accumulator persists in VMEM scratch across k blocks (the
canonical pallas accumulation pattern, same as ops/flash_attention.py).

Measured status (v5e, decode shapes [8,4096]x[4096,14336] in a scan loop,
``benchmarks/bench_int8_matmul.py``): XLA's own dequant-inside-the-loop compiles
to a fused form that beats this kernel (~1.4x vs ~1.2x over bf16), so — same
policy as the flash-attention kernel — the generation path keeps the XLA dequant
(:func:`unionml_tpu.ops.quant.dequantize_tree` inside the step) and this kernel
stays **opt-in** via :func:`quantized_matmul(..., impl="pallas")` until it wins
its benchmark. Off-TPU (or for shapes with no block-aligned tiling) it falls
back to dequant + ``jnp.dot`` with identical numerics.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU plugin module; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["int8_matmul", "quantized_matmul"]

_BLOCK_M = 256
_F_CANDIDATES = (512, 256, 128)
_K_CANDIDATES = (512, 256, 128, 64)  # K also tiles the x block's lane dim


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w = q_ref[:].astype(jnp.bfloat16)  # int8 -> bf16 in VMEM; HBM saw int8 bytes
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:].astype(jnp.bfloat16), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[:] = (acc_ref[:] * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _pick_block(dim: int, candidates) -> Optional[int]:
    for c in candidates:
        if dim % c == 0:
            return c
    return None


def int8_matmul(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    *,
    out_dtype: Any = None,
    interpret: bool = False,
    block_m: Optional[int] = None,
    block_k: Optional[int] = None,
    block_f: Optional[int] = None,
) -> jax.Array:
    """``[M, K] @ int8 [K, F] * f32 [1, F] -> [M, F]`` via the pallas kernel.

    Requires K and F to admit a block tiling (see module docstring); M is padded
    to the block size here (x is small — the weight is never padded or copied).
    Explicit ``block_*`` override the defaults (the shootout benchmark sweeps
    them; dims must divide evenly).
    """
    m, k_dim = x.shape
    _, f_dim = q.shape
    out_dtype = out_dtype or x.dtype
    block_k = block_k or _pick_block(k_dim, _K_CANDIDATES)
    block_f = block_f or _pick_block(f_dim, _F_CANDIDATES)
    if block_k is None or block_f is None:
        raise ValueError(f"no block tiling for weight shape {(k_dim, f_dim)}")
    if k_dim % block_k or f_dim % block_f:
        raise ValueError(f"blocks ({block_k}, {block_f}) do not tile weight {(k_dim, f_dim)}")

    block_m = block_m or min(_BLOCK_M, 1 << (max(m - 1, 0)).bit_length() if m > 1 else 1)
    padded_m = -(-m // block_m) * block_m
    if padded_m != m:
        x = jnp.pad(x, ((0, padded_m - m), (0, 0)))

    grid = (padded_m // block_m, f_dim // block_f, k_dim // block_k)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((padded_m, f_dim), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda mi, fi, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_f), lambda mi, fi, ki: (ki, fi)),
            pl.BlockSpec((1, block_f), lambda mi, fi, ki: (0, fi)),
        ],
        out_specs=pl.BlockSpec((block_m, block_f), lambda mi, fi, ki: (mi, fi)),
        scratch_shapes=[pltpu.VMEM((block_m, block_f), jnp.float32)] if pltpu else [],
        compiler_params=(
            None
            if interpret or pltpu is None
            else pltpu.CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary"))
        ),
        interpret=interpret,
    )(x, q, scale)
    return out[:m] if padded_m != m else out


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return pltpu is not None and jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def quantized_matmul(x: jax.Array, qt: Any, *, out_dtype: Any = None, impl: str = "xla") -> jax.Array:
    """Matmul against a :class:`~unionml_tpu.ops.quant.QuantizedTensor` weight.

    ``impl="xla"`` (default — currently faster, see module docstring) dequantizes
    in-graph and lets XLA fuse; ``impl="pallas"`` uses the kernel (TPU only,
    block-tileable shapes; silently falls back otherwise). ``x`` may carry
    leading batch dims; the weight must be 2D.
    """
    out_dtype = out_dtype or x.dtype
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if (
        impl == "pallas"
        and _on_tpu()
        and _pick_block(qt.q.shape[0], _K_CANDIDATES)
        and _pick_block(qt.q.shape[1], _F_CANDIDATES)
    ):
        out = int8_matmul(x2, qt.q, qt.scale, out_dtype=out_dtype)
    else:
        w = (qt.q.astype(jnp.float32) * qt.scale).astype(out_dtype)
        out = jnp.dot(x2.astype(out_dtype), w)
    return out.reshape(*lead, qt.q.shape[1])
