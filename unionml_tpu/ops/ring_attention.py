"""Ring attention: exact attention over a sequence-sharded context.

Long-context support the reference entirely lacks (SURVEY.md §2.3, §5.7). The sequence
dim is sharded over the ``sequence`` mesh axis; each device holds one Q/K/V block of
shape ``[B, L/s, H, D]``. K/V blocks rotate around the mesh-axis ring with
``lax.ppermute`` (neighbor ICI transfers) while each device accumulates its Q block's
attention with flash-style running softmax statistics — so memory stays O(L/s) per
device and the transfer of the next block overlaps the compute on the current one in
XLA's schedule.

``ring_attention`` is written to run *inside* ``shard_map`` (it needs the named axis);
``sequence_sharded_attention`` is the jit-level wrapper that binds it over a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from unionml_tpu.parallel.collectives import all_to_all, axis_size, ring_permute


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "sequence",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention for sequence-sharded q/k/v. Call inside ``shard_map``.

    :param q, k, v: local blocks ``[B, L_local, H, D]``, the sequence dim sharded over
        ``axis``. Supports grouped-query KV (``Hkv`` dividing ``H``).
    """
    ring_size = axis_size(axis)
    my_index = lax.axis_index(axis)
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5

    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_kv != n_heads:
        k = jnp.repeat(k, n_heads // n_kv, axis=2)
        v = jnp.repeat(v, n_heads // n_kv, axis=2)

    batch, q_len, _, head_dim = q.shape
    k_len = k.shape[1]
    q_pos = my_index * q_len + jnp.arange(q_len)  # global positions of the local Q rows

    qf = q.astype(jnp.float32) * scale

    m = jnp.full((batch, n_heads, q_len, 1), jnp.finfo(jnp.float32).min, dtype=jnp.float32)
    l = jnp.zeros((batch, n_heads, q_len, 1), dtype=jnp.float32)
    acc = jnp.zeros((batch, n_heads, q_len, head_dim), dtype=jnp.float32)

    def attend(step, m, l, acc, k_blk, v_blk):
        # which global block this device holds after ``step`` rotations
        src = (my_index - step) % ring_size
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * k_len + jnp.arange(k_len)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)

        m_curr = jnp.max(scores, axis=-1, keepdims=True)
        m_next = jnp.maximum(m, m_curr)
        alpha = jnp.exp(m - m_next)
        p = jnp.exp(scores - m_next)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return m_next, l, acc

    def body(step, carry):
        # rotate first, then accumulate: the loop runs steps 1..ring_size-1, so only
        # ring_size-1 ppermutes happen — no discarded final K/V transfer
        m, l, acc, k_blk, v_blk = carry
        k_blk, v_blk = ring_permute((k_blk, v_blk), axis)
        m, l, acc = attend(step, m, l, acc, k_blk, v_blk)
        return m, l, acc, k_blk, v_blk

    m, l, acc = attend(0, m, l, acc, k, v)
    m, l, acc, _, _ = lax.fori_loop(1, ring_size, body, (m, l, acc, k, v))
    denom = jnp.where(l == 0.0, 1.0, l)
    out = (acc / denom).astype(q.dtype)  # [B, H, Lq, D]
    return out.transpose(0, 2, 1, 3)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "sequence",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all instead of a ring.

    Two resharding all-to-alls per call: ``[B, L/s, H, D] -> [B, L, H/s, D]``
    (each device gets the FULL sequence for a head subset, dense attention runs
    locally with no per-step communication), then back. Cheaper in collective
    volume than ring attention when heads divide evenly over the axis and the
    full-sequence scores fit in HBM; ring attention remains the O(L/s)-memory
    option for extreme context lengths. Call inside ``shard_map``.
    """
    size = axis_size(axis)
    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_kv != n_heads:  # GQA: expand KV so the head dim reshards evenly
        k = jnp.repeat(k, n_heads // n_kv, axis=2)
        v = jnp.repeat(v, n_heads // n_kv, axis=2)
    if n_heads % size:
        raise ValueError(f"ulysses needs heads ({n_heads}) divisible by axis size ({size})")

    # [B, L/s, H, D] -> [B, L, H/s, D]: head-sharded, sequence-complete
    q_full, k_full, v_full = (all_to_all(t, axis, split_axis=2, concat_axis=1) for t in (q, k, v))

    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q_full.astype(jnp.float32) * scale, k_full.astype(jnp.float32))
    if causal:
        l_full = q_full.shape[1]
        mask = jnp.arange(l_full)[:, None] >= jnp.arange(l_full)[None, :]
        scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v_full.astype(jnp.float32)).astype(q.dtype)
    # [B, L, H/s, D] -> [B, L/s, H, D]
    return all_to_all(out, axis, split_axis=1, concat_axis=2)


def sequence_sharded_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    batch_axes=("data", "fsdp"),
    sequence_axis: str = "sequence",
    impl: str = "ring",
) -> jax.Array:
    """Jit-level sequence-parallel attention: shards sequence over ``sequence_axis``,
    batch over ``batch_axes``, runs :func:`ring_attention` (``impl="ring"``) or
    :func:`ulysses_attention` (``impl="ulysses"``) under ``shard_map``."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    present_batch = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    spec = P(present_batch, sequence_axis, None, None)

    sp_attention = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    fn = functools.partial(sp_attention, axis=sequence_axis, causal=causal)
    try:
        wrapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    except TypeError:  # older API spells the replication-check flag differently
        wrapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
    return wrapped(q, k, v)
