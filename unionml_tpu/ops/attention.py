"""Attention ops: XLA reference implementation + dispatch to the pallas flash kernel.

The reference framework contains no attention code at all (SURVEY.md §5.7 — it never
looks inside a model); our model library needs it for the BERT/Llama/ViT configs, and
on TPU the attention inner loop is the canonical pallas target: keeping the running
softmax statistics in VMEM avoids materializing the [L, L] score matrix in HBM.

Layout convention throughout: ``[batch, length, heads, head_dim]`` (BLHD) — the
sequence dim sits next to batch so sequence-parallel sharding specs stay rank-stable.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention in pure XLA ops (always correct, any backend).

    :param q: ``[B, Lq, H, D]``; ``k``/``v``: ``[B, Lk, H, D]`` (or ``[B, Lk, Hkv, D]``
        with ``H % Hkv == 0`` for grouped-query attention).
    """
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_kv != n_heads:  # grouped-query: repeat KV heads
        k = jnp.repeat(k, n_heads // n_kv, axis=2)
        v = jnp.repeat(v, n_heads // n_kv, axis=2)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    visible = None
    if causal:
        q_idx = jnp.arange(q.shape[1])[:, None]
        k_idx = jnp.arange(k.shape[1])[None, :]
        causal_mask = (q_idx >= (k_idx - (k.shape[1] - q.shape[1])))[None, None]
        scores = jnp.where(causal_mask, scores, jnp.finfo(scores.dtype).min)
        visible = causal_mask
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        visible = mask if visible is None else jnp.logical_and(visible, mask)

    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if visible is None:
        return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    # a row with NO visible keys is zero, not the uniform-softmax mean of V that
    # softmax(-inf row) would produce — matching ring and flash attention
    weights = jnp.where(visible.any(axis=-1, keepdims=True), weights, 0)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


@functools.partial(jax.jit, static_argnames=("causal", "impl"))
def multihead_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention entry point used by the model library.

    ``impl``: ``"xla"`` (reference), ``"flash"`` (pallas kernel, TPU only), or
    ``"auto"``. Measured on v5e (B=4, L=1024, H=8, D=128, bf16) the hand-written
    flash kernel currently trails XLA's fused attention (2.6ms vs 1.6ms), so ``auto``
    resolves to XLA; flash stays opt-in until the kernel wins its benchmark.

    ``mask`` (boolean, broadcastable to ``[B, H, Lq, Lk]``, True = attend) routes to
    the XLA path — the flash kernel has no arbitrary-mask support.
    """
    if impl == "flash" and mask is None:
        from unionml_tpu.ops.flash_attention import flash_attention

        # grouped-query KV passes through unexpanded: the kernel's index maps
        # route query head h to KV head h * n_kv // n_heads
        return flash_attention(q, k, v, causal=causal)
    return dot_product_attention(q, k, v, causal=causal, mask=mask)
