"""Pallas TPU flash attention (forward kernel + recompute backward).

Blocked online-softmax attention: Q/K/V stream HBM->VMEM in (block_q x block_k)
tiles, the running max/denominator and the f32 output accumulator live in VMEM
scratch, and the [L, L] score matrix is never materialized in HBM. The TPU grid is
sequential over its innermost dimension, so scratch persists across the k-block loop
— the canonical pallas accumulation pattern (see /opt/skills/guides/pallas_guide.md,
"Patterns: Double Buffering" / grid accumulation).

Layout decisions (each mandated by the TPU memory system):

- the kernel indexes ``[B, L, H, D]`` inputs directly with a 4D grid
  ``(batch, heads, q_blocks, k_blocks)`` — no head-folding transpose, so Q/K/V
  never take an extra HBM round trip before/after the kernel;
- grouped-query attention happens in the K/V index maps (query head ``h`` reads
  KV head ``h * n_kv // n_heads``) — repeated KV heads are never materialized;
- ``dimension_semantics`` marks batch/head/q-block dims parallel and the k-block
  dim arbitrary (sequential accumulation), letting Mosaic pipeline the grid;
- running-stats scratch is lane-replicated ``(block_q, 128)`` — a ``(block_q, 1)``
  buffer pads to a full lane register anyway and forces relayouts.

Backward: ``jax.custom_vjp`` recomputes attention with the XLA reference
implementation and differentiates through it — the memory win of the flash forward is
preserved for inference and for activations under ``jax.checkpoint``; a fused pallas
backward kernel is a later optimization.

Shapes: ``q: [B, Lq, H, D]``, ``k/v: [B, Lk, Hkv, D]`` with ``H % Hkv == 0``,
``D % 128 == 0``, and lengths divisible by the block size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU plugin module; without it the kernel (interpret mode included) is unusable
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_LANES = 128  # TPU vector lane width: stats scratch is lane-replicated
_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch, *, causal, block_q, block_k, scale, offset
):
    # offset = k_len - q_len: with unequal lengths, query row i may attend keys up to
    # i + offset (matching dot_product_attention's shifted diagonal)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _compute():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [block_q, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)  # [block_k, D]
        scores = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [block_q, block_k]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(q_pos + offset >= k_pos, scores, _NEG_INF)

        m_prev = m_scratch[:, :1]  # [block_q, 1] view of the lane-replicated stats
        l_prev = l_scratch[:, :1]
        m_curr = jnp.max(scores, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)

        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())))
        m_scratch[:] = jnp.broadcast_to(m_next, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_next, l_scratch.shape)

    if causal:
        # skip k blocks entirely above the (offset-shifted) diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_final = l_scratch[:, :1]
        denom = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[0, :, 0, :] = (acc_scratch[:] / denom).astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, interpret: bool) -> jax.Array:
    batch, q_len, n_heads, head_dim = q.shape
    k_len, n_kv = k.shape[1], k.shape[2]
    if n_heads % n_kv:
        raise ValueError(f"query heads ({n_heads}) must be a multiple of KV heads ({n_kv})")
    block_q = min(DEFAULT_BLOCK_Q, q_len)
    block_k = min(DEFAULT_BLOCK_K, k_len)
    scale = head_dim**-0.5

    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable; use impl='xla' attention instead")

    # 4D grid over [B, L, H, D] directly — no head-folding transpose; KV heads are
    # resolved in the index maps (GQA without materializing repeats)
    grid = (batch, n_heads, q_len // block_q, k_len // block_k)

    def q_index(b, h, qi, ki):
        return (b, qi, h, 0)

    def kv_index(b, h, qi, ki):
        return (b, ki, h * n_kv // n_heads, 0)

    kernel = functools.partial(
        _flash_fwd_kernel, causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=k_len - q_len
    )
    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, 1, head_dim), q_index),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, head_dim), q_index),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    return _flash_forward(q, k, v, causal, interpret)


def _flash_fwd_rule(q, k, v, causal, interpret):
    return _flash_forward(q, k, v, causal, interpret), (q, k, v)


def _flash_bwd_rule(causal, interpret, residuals, g):
    from unionml_tpu.ops.attention import dot_product_attention

    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: dot_product_attention(q_, k_, v_, causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False, interpret: bool = False
) -> jax.Array:
    """Flash attention entry point. ``interpret=True`` runs the kernel in the pallas
    interpreter (CPU) — used by the test ring. Accepts grouped-query KV
    (``k/v: [B, Lk, Hkv, D]`` with ``Hkv`` dividing the query head count)."""
    return _flash(q, k, v, causal, interpret)
