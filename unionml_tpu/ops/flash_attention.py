"""Pallas TPU flash attention (forward kernel + recompute backward).

Blocked online-softmax attention: Q/K/V stream HBM->VMEM in (block_q x block_k)
tiles, the running max/denominator and the f32 output accumulator live in VMEM
scratch, and the [L, L] score matrix is never materialized in HBM. The TPU grid is
sequential over its innermost dimension, so scratch persists across the k-block loop
— the canonical pallas accumulation pattern (see /opt/skills/guides/pallas_guide.md,
"Patterns: Double Buffering" / grid accumulation).

Backward: ``jax.custom_vjp`` recomputes attention with the XLA reference
implementation and differentiates through it — the memory win of the flash forward is
preserved for inference and for activations under ``jax.checkpoint``; a fused pallas
backward kernel is a later optimization.

Shapes: ``q, k, v: [B, L, H, D]`` with ``D % 128 == 0`` and ``L`` divisible by the
block size. Grouped-query is handled by the caller (head repetition) before dispatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; fall back to interpreter-friendly defaults on CPU
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch, *, causal, block_q, block_k, scale, offset
):
    # offset = k_len - q_len: with unequal lengths, query row i may attend keys up to
    # i + offset (matching dot_product_attention's shifted diagonal)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)  # [block_k, D]
        scores = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [block_q, block_k]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(q_pos + offset >= k_pos, scores, _NEG_INF)

        m_prev = m_scratch[:]  # [block_q, 1]
        m_curr = jnp.max(scores, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)

        l_next = l_scratch[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())))
        m_scratch[:] = m_next
        l_scratch[:] = l_next

    if causal:
        # skip k blocks entirely above the (offset-shifted) diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        denom = jnp.where(l_scratch[:] == 0.0, 1.0, l_scratch[:])
        o_ref[0] = (acc_scratch[:] / denom).astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, interpret: bool) -> jax.Array:
    batch, q_len, n_heads, head_dim = q.shape
    k_len = k.shape[1]
    block_q = min(DEFAULT_BLOCK_Q, q_len)
    block_k = min(DEFAULT_BLOCK_K, k_len)
    scale = head_dim**-0.5

    # fold heads into batch; kernel operates on [BH, L, D]
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(x.shape[0] * x.shape[2], x.shape[1], x.shape[3])

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (batch * n_heads, q_len // block_q, k_len // block_k)

    kernel = functools.partial(
        _flash_fwd_kernel, causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=k_len - q_len
    )
    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable; use impl='xla' attention instead")
    scratch_shapes = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, head_dim), jnp.float32),
    ]

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qf, kf, vf)

    return out.reshape(batch, n_heads, q_len, head_dim).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    return _flash_forward(q, k, v, causal, interpret)


def _flash_fwd_rule(q, k, v, causal, interpret):
    return _flash_forward(q, k, v, causal, interpret), (q, k, v)


def _flash_bwd_rule(causal, interpret, residuals, g):
    from unionml_tpu.ops.attention import dot_product_attention

    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: dot_product_attention(q_, k_, v_, causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False, interpret: bool = False
) -> jax.Array:
    """Flash attention entry point. ``interpret=True`` runs the kernel in the pallas
    interpreter (CPU) — used by the test ring."""
    return _flash(q, k, v, causal, interpret)
