"""Pallas TPU flash attention (forward kernel + recompute backward).

Blocked online-softmax attention: Q/K/V stream HBM->VMEM in (block_q x block_k)
tiles, the running max/denominator and the f32 output accumulator live in VMEM
scratch, and the [L, L] score matrix is never materialized in HBM. The TPU grid is
sequential over its innermost dimension, so scratch persists across the k-block loop
— the canonical pallas accumulation pattern (see /opt/skills/guides/pallas_guide.md,
"Patterns: Double Buffering" / grid accumulation).

Layout decisions (each mandated by the TPU memory system):

- the kernel indexes ``[B, L, H, D]`` inputs directly with a 4D grid
  ``(batch, heads, q_blocks, k_blocks)`` — no head-folding transpose, so Q/K/V
  never take an extra HBM round trip before/after the kernel;
- grouped-query attention happens in the K/V index maps (query head ``h`` reads
  KV head ``h * n_kv // n_heads``) — repeated KV heads are never materialized;
- ``dimension_semantics`` marks batch/head/q-block dims parallel and the k-block
  dim arbitrary (sequential accumulation), letting Mosaic pipeline the grid;
- running-stats scratch is lane-replicated ``(block_q, 128)`` — a ``(block_q, 1)``
  buffer pads to a full lane register anyway and forces relayouts.

Backward: fused FlashAttention-2-style pallas kernels. The forward additionally
saves the per-row logsumexp (``[B, H, Lq]``, lane-major blocks); the backward
recomputes scores blockwise from it (``P = exp(S - lse)``), so the ``[L, L]``
matrix never exists in HBM in either direction — training memory stays
O(L * D + L), which is the whole point for long context. Two kernels:

- ``dq``: grid ``(b, h, q_blocks, k_blocks)``, accumulating over k blocks;
- ``dk/dv``: grid ``(b, h, k_blocks, q_blocks)``, accumulating over q blocks,
  computed at full query-head resolution and group-summed afterward for GQA
  (``jnp.repeat``'s transpose is a segment sum).

``delta = rowsum(dO * O)`` (the softmax-Jacobian correction) is one cheap
elementwise XLA reduction outside the kernels.

Shapes: ``q: [B, Lq, H, D]``, ``k/v: [B, Lk, Hkv, D]`` with ``H % Hkv == 0``,
``D % 128 == 0``, and lengths divisible by the block size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU plugin module; without it the kernel (interpret mode included) is unusable
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_LANES = 128  # TPU vector lane width: stats scratch is lane-replicated
_NEG_INF = float(jnp.finfo(jnp.float32).min)
_BIG = 1e30  # lse sentinel for fully-masked rows: exp(S - BIG) == 0


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scratch, l_scratch, acc_scratch, *, causal, block_q, block_k, scale, offset
):
    # offset = k_len - q_len: with unequal lengths, query row i may attend keys up to
    # i + offset (matching dot_product_attention's shifted diagonal)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _compute():
        # matmuls run in the INPUT dtype with f32 accumulation: a bf16 QK^T /
        # PV hits the MXU's native rate, while an up-front f32 cast would halve
        # it — the whole reason the hand kernel can beat XLA's fused attention.
        # Scale is applied to the f32 scores, not the bf16 operands.
        q = q_ref[0, :, 0, :]  # [block_q, D]
        k = k_ref[0, :, 0, :]  # [block_k, D]
        v = v_ref[0, :, 0, :]  # [block_k, D]
        scores = (
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )  # [block_q, block_k] f32

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(q_pos + offset >= k_pos, scores, _NEG_INF)

        m_prev = m_scratch[:, :1]  # [block_q, 1] view of the lane-replicated stats
        l_prev = l_scratch[:, :1]
        m_curr = jnp.max(scores, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)

        l_next = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_next, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_next, l_scratch.shape)

    if causal:
        # skip k blocks entirely above the (offset-shifted) diagonal
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_final = l_scratch[:, :1]
        denom = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[0, :, 0, :] = (acc_scratch[:] / denom).astype(o_ref.dtype)
        # logsumexp per row, saved for the fused backward: P = exp(S - lse).
        # Fully-masked rows get +BIG so the backward's exp underflows to 0.
        lse = jnp.where(
            l_final == 0.0, jnp.float32(_BIG), m_scratch[:, :1] + jnp.log(denom)
        )
        lse_ref[0, 0, :] = lse[:, 0]


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool, interpret: bool, blocks=None
) -> "tuple[jax.Array, jax.Array]":
    batch, q_len, n_heads, head_dim = q.shape
    k_len, n_kv = k.shape[1], k.shape[2]
    if n_heads % n_kv:
        raise ValueError(f"query heads ({n_heads}) must be a multiple of KV heads ({n_kv})")
    block_q = min((blocks or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))[0], q_len)
    block_k = min((blocks or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))[1], k_len)
    if q_len % block_q or k_len % block_k:
        # a silently floor-divided grid would leave tail rows unwritten
        raise ValueError(f"blocks ({block_q}, {block_k}) do not tile lengths ({q_len}, {k_len})")
    scale = head_dim**-0.5

    if pltpu is None:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable; use impl='xla' attention instead")

    # 4D grid over [B, L, H, D] directly — no head-folding transpose; KV heads are
    # resolved in the index maps (GQA without materializing repeats)
    grid = (batch, n_heads, q_len // block_q, k_len // block_k)

    def q_index(b, h, qi, ki):
        return (b, qi, h, 0)

    def kv_index(b, h, qi, ki):
        return (b, ki, h * n_kv // n_heads, 0)

    kernel = functools.partial(
        _flash_fwd_kernel, causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=k_len - q_len
    )

    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, n_heads, q_len), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, 1, head_dim), q_index),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, 1, head_dim), q_index),
            pl.BlockSpec((1, 1, block_q), lambda b, h, qi, ki: (b, h, qi)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _compiler_params(interpret: bool):
    if interpret:
        return None
    return pltpu.CompilerParams(dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _bwd_recompute(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki, *, causal, block_q, block_k, scale, offset):
    """Shared backward prologue: recompute P = exp(S - lse) for one (qi, ki) tile
    and return (q, k, ds, p, do) — operands in the input dtype (MXU-native),
    p/ds in f32 — the dq and dk/dv kernels consume the same quantities, so
    masking/recompute fixes land in exactly one place."""
    q = q_ref[0, :, 0, :]
    k = k_ref[0, :, 0, :]
    v = v_ref[0, :, 0, :]
    do = do_ref[0, :, 0, :]
    lse = lse_ref[0, 0, :][:, None]  # [block_q, 1]
    delta = delta_ref[0, 0, :][:, None]

    scores = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos + offset >= k_pos, scores, _NEG_INF)
    p = jnp.exp(scores - lse)  # [block_q, block_k] f32; 0 for masked rows (lse=BIG)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return q, k, ds, p, do


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, causal, block_q, block_k, scale, offset
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        _, k, ds, _, _ = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=offset,
        )
        dq_acc[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0, :, 0, :] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
    *, causal, block_q, block_k, scale, offset,
):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    num_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q, _, ds, p, do = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=offset,
        )
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_acc[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # skip q blocks entirely above this k block's (offset-shifted) diagonal
        @pl.when(qi * block_q + block_q - 1 + offset >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, :, 0, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, interpret: bool, blocks=None):
    """FlashAttention-2-style fused backward: scores recomputed blockwise from the
    saved logsumexp — the [L, L] matrix never touches HBM (the XLA autodiff
    fallback materializes it, erasing the forward's memory win for training).
    ``blocks`` follows the forward's override so a shape legal under custom
    forward tiles can never leave backward tail rows unwritten."""
    batch, q_len, n_heads, head_dim = q.shape
    k_len, n_kv = k.shape[1], k.shape[2]
    block_q = min((blocks or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))[0], q_len)
    block_k = min((blocks or (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K))[1], k_len)
    if q_len % block_q or k_len % block_k:
        raise ValueError(f"blocks ({block_q}, {block_k}) do not tile lengths ({q_len}, {k_len})")
    scale = head_dim**-0.5
    offset = k_len - q_len

    # delta_i = rowsum(dO_i * O_i), the dS correction term; [B, H, Lq] like lse
    delta = jnp.einsum(
        "blhd,blhd->bhl", g.astype(jnp.float32), out.astype(jnp.float32)
    )

    def q_index(b, h, qi, ki):
        return (b, qi, h, 0)

    def kv_index_dq(b, h, qi, ki):
        return (b, ki, h * n_kv // n_heads, 0)

    def stats_index(b, h, qi, ki):
        return (b, h, qi)

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=offset
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(batch, n_heads, q_len // block_q, k_len // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, 1, head_dim), q_index),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index_dq),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index_dq),
            pl.BlockSpec((1, block_q, 1, head_dim), q_index),
            pl.BlockSpec((1, 1, block_q), stats_index),
            pl.BlockSpec((1, 1, block_q), stats_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, head_dim), q_index),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dk/dv accumulate over q blocks (qi innermost); computed at full query-head
    # resolution, then group-summed for GQA (repeat's transpose is a sum)
    def kv_index_dkv(b, h, ki, qi):
        return (b, ki, h * n_kv // n_heads, 0)

    dk_full, dv_full = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, causal=causal, block_q=block_q, block_k=block_k, scale=scale, offset=offset
        ),
        out_shape=(
            jax.ShapeDtypeStruct((batch, k_len, n_heads, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch, k_len, n_heads, head_dim), v.dtype),
        ),
        grid=(batch, n_heads, k_len // block_k, q_len // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, 1, head_dim), lambda b, h, ki, qi: (b, qi, h, 0)),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index_dkv),
            pl.BlockSpec((1, block_k, 1, head_dim), kv_index_dkv),
            pl.BlockSpec((1, block_q, 1, head_dim), lambda b, h, ki, qi: (b, qi, h, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, h, ki, qi: (b, h, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b, h, ki, qi: (b, h, qi)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, 1, head_dim), lambda b, h, ki, qi: (b, ki, h, 0)),
            pl.BlockSpec((1, block_k, 1, head_dim), lambda b, h, ki, qi: (b, ki, h, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    if n_kv != n_heads:
        group = n_heads // n_kv
        dk = dk_full.reshape(batch, k_len, n_kv, group, head_dim).sum(axis=3).astype(k.dtype)
        dv = dv_full.reshape(batch, k_len, n_kv, group, head_dim).sum(axis=3).astype(v.dtype)
    else:
        dk, dv = dk_full, dv_full
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, interpret, blocks):
    out, _ = _flash_forward(q, k, v, causal, interpret, blocks)
    return out


def _flash_fwd_rule(q, k, v, causal, interpret, blocks):
    out, lse = _flash_forward(q, k, v, causal, interpret, blocks)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, interpret, blocks, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, out, lse, g, causal, interpret, blocks)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    interpret: bool = False,
    blocks: "tuple[int, int] | None" = None,
) -> jax.Array:
    """Flash attention entry point. ``interpret=True`` runs the kernel in the pallas
    interpreter (CPU) — used by the test ring. Accepts grouped-query KV
    (``k/v: [B, Lk, Hkv, D]`` with ``Hkv`` dividing the query head count).
    ``blocks=(block_q, block_k)`` overrides the forward tile sizes (the shootout
    benchmark sweeps them; lengths must divide evenly)."""
    return _flash(q, k, v, causal, interpret, blocks)
