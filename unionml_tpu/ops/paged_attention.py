"""Paged-attention decode over heads-major block pools.

The gather path in :meth:`unionml_tpu.models.layers.Attention._paged_cached_attention`
materializes ``pool[table]`` — a full logical-layout copy of every resident
row's K/V per layer per step — before attending. This module routes the decode
read through the pallas paged-attention kernel that ships with JAX
(``jax.experimental.pallas.ops.tpu.paged_attention``, the production TPU
serving kernel): it DMAs exactly the pages each row's table names, streams them
block-by-block through flash-style online softmax, and never materializes the
gathered copy — decode KV traffic drops to one pool read.

The pool layout (``[H_kv, n_pages, page_size, D]``,
:func:`unionml_tpu.models.generate.init_paged_cache`) matches the kernel's
expectation, so dispatch is zero-copy. TPU-only (the kernel has no interpret
mode); the portable gather path remains the default until the kernel wins its
shootout (``benchmarks/bench_paged_attention.py``) — the same auto policy as
:mod:`unionml_tpu.ops.flash_attention`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["paged_decode_attention"]


def _pages_per_block(pages_per_sequence: int, target: int = 8) -> int:
    """Largest divisor of ``pages_per_sequence`` that is <= ``target`` (the
    kernel requires an exact tiling of the table width)."""
    for candidate in range(min(target, pages_per_sequence), 0, -1):
        if pages_per_sequence % candidate == 0:
            return candidate
    return 1


def paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    lengths: jax.Array,
    page_indices: jax.Array,
    *,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
    pages_per_compute_block: Optional[int] = None,
) -> jax.Array:
    """One decode step of attention over paged K/V.

    ``q: [B, H, D]``, ``k_pages/v_pages: [H_kv, n_pages, page_size, D]``,
    ``lengths: [B] int32`` (visible positions per row, INCLUDING the token just
    written), ``page_indices: [B, pages_per_sequence] int32``. Returns
    ``[B, H, D]``. Grouped-query attention is native (``H % H_kv == 0``).

    ``k_scales``/``v_scales`` (``[H_kv, n_pages, page_size, 1]`` f32, OUR int8
    convention: ``dequant = int8 * scale``) switch to the kernel's quantized
    page path; our scales map exactly via ``h = scale * 127.5`` (the kernel
    dequantizes ``int8 * h / 127.5``). CAVEAT: the library broadcasts the
    scales to FULL head width before launch and DMAs them per page, so int8
    pages cost ~5 B/elem of traffic vs bf16's 2 — the mode exists for the
    shootout's measurement, not as a recommended production path.

    The library kernel computes RAW ``qk`` logits (no softmax scale anywhere in
    ``paged_flash_attention_kernel``), so ``q`` is pre-scaled by
    ``head_dim ** -0.5`` here — numerics then match
    :func:`unionml_tpu.ops.attention.dot_product_attention` and the gather path.
    """
    from jax.experimental.pallas.ops.tpu.paged_attention import paged_attention
    from jax.experimental.pallas.ops.tpu.paged_attention import quantization_utils

    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together")
    if k_scales is not None:
        k_pages = quantization_utils.QuantizedTensor(
            weight=k_pages, scales=(k_scales * quantization_utils.MAX_INT8).astype(jnp.float32)
        )
        v_pages = quantization_utils.QuantizedTensor(
            weight=v_pages, scales=(v_scales * quantization_utils.MAX_INT8).astype(jnp.float32)
        )
    ppcb = pages_per_compute_block or _pages_per_block(page_indices.shape[1])
    scale = q.shape[-1] ** -0.5
    return paged_attention(
        (q * scale).astype(q.dtype),
        k_pages,
        v_pages,
        lengths.astype(jnp.int32),
        page_indices,
        pages_per_compute_block=ppcb,
    )
