"""Vision Transformer image classifier.

Backs BASELINE.json config 5 ("ViT-L image classifier, reader -> HBM prefetch").
Patchify-by-conv keeps the embedding a single MXU-friendly convolution; everything
after reuses the shared encoder blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from unionml_tpu.models.layers import TransformerBlock
from unionml_tpu.parallel.sharding import PartitionRules


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    hidden_dim: int = 4096
    num_classes: int = 1000
    channels: int = 3
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def large(cls, **overrides: Any) -> "ViTConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides: Any) -> "ViTConfig":
        defaults = dict(image_size=32, patch_size=8, dim=128, n_layers=2, n_heads=4, hidden_dim=256, num_classes=10)
        defaults.update(overrides)
        return cls(**defaults)


class ViT(nn.Module):
    """Images ``[B, H, W, C]`` -> class logits ``[B, num_classes]``."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.config
        x = ViTEmbed(cfg, name="embed")(images)
        for i in range(cfg.n_layers):
            x = TransformerBlock(
                n_heads=cfg.n_heads,
                hidden_dim=cfg.hidden_dim,
                decoder=False,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name=f"layer_{i}",
            )(x)
        return ViTHead(cfg, name="head")(x)


class ViTEmbed(nn.Module):
    """Patchify + cls token + position embedding: images -> ``[B, 1+n_patches, dim]``."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.config
        x = nn.Conv(
            cfg.dim,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        batch = x.shape[0]
        x = x.reshape(batch, -1, cfg.dim)
        cls_token = self.param("cls_token", nn.initializers.zeros, (1, 1, cfg.dim), cfg.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls_token.astype(cfg.dtype), (batch, 1, cfg.dim)), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], cfg.dim), cfg.param_dtype)
        return x + pos.astype(cfg.dtype)


class ViTStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` encoder blocks, shape/dtype-preserving
    (the contract :func:`unionml_tpu.parallel.pipeline.pipeline_apply` requires)."""

    config: ViTConfig
    layers_per_stage: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        for i in range(self.layers_per_stage):
            x = TransformerBlock(
                n_heads=cfg.n_heads,
                hidden_dim=cfg.hidden_dim,
                decoder=False,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name=f"layer_{i}",
            )(x)
        return x


class ViTHead(nn.Module):
    """Final norm + classification head on the cls token."""

    config: ViTConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        return nn.Dense(cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="head")(x[:, 0])


class PipelinedViT:
    """ViT partitioned for pipeline parallelism over the ``pipe`` mesh axis.

    Not an ``nn.Module``: the stage stack is a *stacked* parameter tree driven by
    :func:`unionml_tpu.parallel.pipeline.pipeline_apply` (SPMD pipeline, ppermute
    rotation), which has no module-tree analog. The embed/head run replicated outside
    the pipeline; params tree is ``{"embed": ..., "stages": [S, ...], "head": ...}``.
    """

    def __init__(self, config: ViTConfig, n_stages: int, n_microbatches: int = 4):
        if config.n_layers % n_stages:
            raise ValueError(f"n_layers={config.n_layers} not divisible by n_stages={n_stages}")
        self.config = config
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.embed = ViTEmbed(config)
        self.stage = ViTStage(config, layers_per_stage=config.n_layers // n_stages)
        self.head = ViTHead(config)

    def init(self, rng: jax.Array, images: jax.Array) -> Any:
        from unionml_tpu.parallel.pipeline import init_stage_params

        k_embed, k_stage, k_head = jax.random.split(rng, 3)
        embedded = self.embed.init(k_embed, images)
        sample = self.embed.apply(embedded, images[:1])
        return {
            "embed": embedded["params"],
            "stages": init_stage_params(self.stage, k_stage, sample, self.n_stages),
            "head": self.head.init(k_head, sample)["params"],
        }

    def apply(self, params: Any, images: jax.Array, mesh: Any, rules: Any = None) -> jax.Array:
        """Forward pass. Pass the same ``rules`` used to place ``params`` so the
        stage stack stays sharded at rest over fsdp/model inside the pipeline
        (each device transiently all-gathers only its own stage); without rules the
        stage params must be replicated over the non-pipe axes."""
        from unionml_tpu.parallel.pipeline import pipeline_apply

        x = self.embed.apply({"params": params["embed"]}, images)
        stage_fn = lambda p, h: self.stage.apply({"params": p}, h)  # noqa: E731
        param_specs = stage_param_specs(params["stages"], rules) if rules is not None else None
        x = pipeline_apply(
            stage_fn,
            params["stages"],
            x,
            mesh,
            n_microbatches=self.n_microbatches,
            param_specs=param_specs,
        )
        return self.head.apply({"params": params["head"]}, x)


def stage_param_specs(stage_params: Any, rules: PartitionRules, prefix: str = "stages/") -> Any:
    """Resolve the PartitionSpec pytree for a stacked-stage subtree from a rule table
    whose patterns are written against full-tree paths (``stages/...``)."""
    from jax.sharding import PartitionSpec

    from unionml_tpu.parallel.sharding import _path_str

    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(stage_params)
    specs = [rules.spec_for(prefix + _path_str(path)) or PartitionSpec("pipe") for path, _ in paths_leaves]
    return jax.tree_util.tree_unflatten(treedef, specs)


def pipelined_vit_partition_rules() -> PartitionRules:
    """Rules for the ``PipelinedViT`` params tree: stacked stages gain a leading
    ``pipe`` entry; embed/head replicate (they are small relative to the stack)."""
    from unionml_tpu.parallel.pipeline import pipeline_rule_table

    stage_rules = [
        (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
        (r"attn/o_proj/kernel", P("model", "fsdp")),
        (r"mlp/wi/kernel", P("fsdp", "model")),
        (r"mlp/wo/kernel", P("model", "fsdp")),
    ]
    return PartitionRules(
        pipeline_rule_table(stage_rules)
        + [
            (r"embed/patch_embed/kernel", P(None, None, None, "fsdp")),
            (r"head/head/kernel", P("fsdp", None)),
            (r".*", P()),
        ]
    )


def vit_partition_rules() -> PartitionRules:
    return PartitionRules(
        [
            (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
            (r"attn/o_proj/kernel", P("model", "fsdp")),
            (r"mlp/wi/kernel", P("fsdp", "model")),
            (r"mlp/wo/kernel", P("model", "fsdp")),
            (r"patch_embed/kernel", P(None, None, None, "fsdp")),
            (r"head/kernel", P("fsdp", None)),
            (r".*(norm|scale|bias|cls_token|pos_embed)", P()),
        ]
    )
