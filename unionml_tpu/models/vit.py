"""Vision Transformer image classifier.

Backs BASELINE.json config 5 ("ViT-L image classifier, reader -> HBM prefetch").
Patchify-by-conv keeps the embedding a single MXU-friendly convolution; everything
after reuses the shared encoder blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from unionml_tpu.models.layers import TransformerBlock
from unionml_tpu.parallel.sharding import PartitionRules


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    dim: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    hidden_dim: int = 4096
    num_classes: int = 1000
    channels: int = 3
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def large(cls, **overrides: Any) -> "ViTConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides: Any) -> "ViTConfig":
        defaults = dict(image_size=32, patch_size=8, dim=128, n_layers=2, n_heads=4, hidden_dim=256, num_classes=10)
        defaults.update(overrides)
        return cls(**defaults)


class ViT(nn.Module):
    """Images ``[B, H, W, C]`` -> class logits ``[B, num_classes]``."""

    config: ViTConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.config
        x = nn.Conv(
            cfg.dim,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        batch = x.shape[0]
        x = x.reshape(batch, -1, cfg.dim)  # [B, n_patches, dim]

        cls_token = self.param("cls_token", nn.initializers.zeros, (1, 1, cfg.dim), cfg.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls_token.astype(cfg.dtype), (batch, 1, cfg.dim)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], cfg.dim), cfg.param_dtype
        )
        x = x + pos.astype(cfg.dtype)

        for i in range(cfg.n_layers):
            x = TransformerBlock(
                n_heads=cfg.n_heads,
                hidden_dim=cfg.hidden_dim,
                decoder=False,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name=f"layer_{i}",
            )(x)

        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        return nn.Dense(cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="head")(x[:, 0])


def vit_partition_rules() -> PartitionRules:
    return PartitionRules(
        [
            (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
            (r"attn/o_proj/kernel", P("model", "fsdp")),
            (r"mlp/wi/kernel", P("fsdp", "model")),
            (r"mlp/wo/kernel", P("model", "fsdp")),
            (r"patch_embed/kernel", P(None, None, None, "fsdp")),
            (r"head/kernel", P("fsdp", None)),
            (r".*(norm|scale|bias|cls_token|pos_embed)", P()),
        ]
    )
