"""Greedy speculative decoding: a small draft model proposes, the target verifies.

Standard draft-and-verify (Leviathan et al.-style, greedy specialization): per
round the draft model decodes ``gamma`` tokens autoregressively (cheap — small
model), then the target model scores all ``gamma + 1`` positions in ONE cached
forward (the same HBM traffic as a single decode step at small batch: decode is
weight-bandwidth bound, so verifying gamma+1 tokens costs roughly one token).
The longest prefix where draft and target argmax agree is accepted, plus the
target's own next token as the correction/bonus — so every round emits between
1 and gamma+1 tokens and the output is **exactly** the target-only greedy
sequence (the oracle the tests pin).

TPU-native specifics:

- both models follow the shared cache contract (``unionml_tpu.models.generate``),
  so rollback is free: per-example ``lengths`` simply advance by each row's
  accepted count, and stale K/V beyond that is invisible (visibility mask is
  ``slot <= position``) and overwritten by later writes — no copying, no
  per-row cache surgery, and rows with different acceptance counts coexist in
  one batch;
- the whole post-prefill generation is ONE jitted ``lax.while_loop`` dispatch
  (per-round host round trips through a remote-TPU tunnel measured ~20x the
  round's compute); every shape is static and emitted tokens land in a device
  output buffer via per-row ``dynamic_update_slice`` at each row's ``produced``
  offset;
- eos handling matches :class:`~unionml_tpu.models.generate.Generator`: the
  first eos in a round truncates that row's emission and marks it done.

Sampling (temperature > 0) requires distribution-level rejection sampling and is
not implemented — construct with a greedy config or use the plain Generator.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from unionml_tpu.models.generate import GenerationConfig, Generator

__all__ = ["SpeculativeGenerator"]


class SpeculativeGenerator:
    """Greedy speculative decoding over a (target, draft) model pair.

    >>> spec = SpeculativeGenerator(target, target_params, draft, draft_params,
    ...                             GenerationConfig(max_new_tokens=128, temperature=0.0),
    ...                             gamma=4)
    >>> tokens = spec(prompts)          # == Generator(target, ...)(prompts), faster

    ``rounds`` / ``accepted_tokens`` counters expose the realized acceptance rate
    (``accepted_tokens / (rounds * gamma)``).
    """

    def __init__(
        self,
        target_module: Any,
        target_params: Any,
        draft_module: Any,
        draft_params: Any,
        config: GenerationConfig = GenerationConfig(temperature=0.0),
        *,
        gamma: int = 4,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        quantize: Optional[str] = None,
    ):
        if config.temperature != 0.0:
            raise NotImplementedError("speculative decoding is greedy-only; use temperature=0")
        if gamma < 1:
            raise ValueError("gamma must be >= 1")
        self.config = config
        self.gamma = gamma
        self.rounds = 0
        self.accepted_tokens = 0
        # reuse the Generator machinery for prefill/placement/bucketing on both
        # models; the draft runs unquantized (it is small by construction)
        self._target = Generator(
            target_module, target_params, config,
            mesh=mesh, partition_rules=partition_rules, quantize=quantize,
        )
        self._draft = Generator(draft_module, draft_params, config, mesh=mesh, partition_rules=partition_rules)
        self._round_fn = None

    # ------------------------------------------------------------------ round

    def _build_round(self):
        gamma = int(self.gamma)
        cfg = self.config
        target, draft = self._target, self._draft
        pad = jnp.int32(cfg.pad_id)
        eos = cfg.eos_id

        def draft_apply(p, tok, positions, cache):
            hidden, cache = draft.module.apply(
                {"params": p}, tok, positions=positions, return_hidden=True,
                cache=cache, token_mask=None,
            )
            kernel = p["lm_head"]["kernel"]
            return (hidden @ kernel.astype(hidden.dtype)).astype(jnp.float32), cache

        def target_apply(p, tok, positions, cache, token_mask):
            hidden, cache = target.module.apply(
                {"params": p}, tok, positions=positions, return_hidden=True,
                cache=cache, token_mask=token_mask,
            )
            kernel = p["lm_head"]["kernel"]
            return (hidden @ kernel.astype(hidden.dtype)).astype(jnp.float32), cache

        def spec_round(tp, dp, t_cache, d_cache, tok, lengths, done, produced, out_buf):

            # --- draft: gamma greedy steps (small-model cached decode) ---
            def draft_body(carry, _):
                cache, t, ln = carry
                logits, cache = draft_apply(dp, t[:, None], ln[:, None], cache)
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                return (cache, nxt, ln + 1), nxt

            (d_cache, _, _), drafts = jax.lax.scan(
                draft_body, (d_cache, tok, lengths), None, length=gamma
            )
            drafts = drafts.T  # [B, gamma]

            # --- target: verify tok + all gamma drafts in one cached forward ---
            inputs = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B, gamma+1]
            positions = lengths[:, None] + jnp.arange(gamma + 1)[None]
            logits, t_cache = target_apply(tp, inputs, positions, t_cache, (~done)[:, None])
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, gamma+1]

            # longest agreeing prefix: a[b] = #{i : drafts[b, :i+1] == greedy[b, :i+1]}
            match = jnp.cumprod((drafts == greedy[:, :gamma]).astype(jnp.int32), axis=1)
            accepted = match.sum(axis=1)  # [B] in [0, gamma]

            # emitted tokens this round: greedy[:, :accepted+1] then pads
            idx = jnp.arange(gamma + 1)[None]
            emit_mask = idx <= accepted[:, None]
            emitted = jnp.where(emit_mask, greedy, pad)
            if eos is not None:
                is_eos = (emitted == eos) & emit_mask
                # truncate after the first eos: positions strictly beyond it emit pad
                seen_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
                emit_mask = emit_mask & (seen_before == 0)
                emitted = jnp.where(emit_mask, emitted, pad)
                row_hits_eos = is_eos.any(axis=1)
            else:
                row_hits_eos = jnp.zeros_like(done)
            emitted = jnp.where(done[:, None], pad, emitted)
            n_emit = jnp.where(done, 0, emit_mask.sum(axis=1))

            # clip to the generation budget
            room = jnp.maximum(cfg.max_new_tokens - produced, 0)
            n_emit = jnp.minimum(n_emit, room)
            emitted = jnp.where(idx < n_emit[:, None], emitted, pad)

            out_buf = jax.vmap(
                lambda buf, row, start: jax.lax.dynamic_update_slice(buf, row, (start,))
            )(out_buf, emitted, produced)

            new_done = done | row_hits_eos | (produced + n_emit >= cfg.max_new_tokens)
            # next round continues after the last emitted token; finished rows freeze
            tok = jnp.where(
                new_done, tok, jnp.take_along_axis(emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            )
            lengths = lengths + jnp.where(done, 0, n_emit)
            produced = produced + n_emit
            acc_count = jnp.where(done, 0, jnp.minimum(accepted, room)).sum()
            return t_cache, d_cache, tok, lengths, new_done, produced, out_buf, acc_count

        def spec_loop(tp, dp, t_cache, d_cache, tok, lengths, done, produced, out_buf):
            """The full post-prefill generation as ONE device-side while_loop —
            per-round host round trips through a remote-TPU tunnel would otherwise
            dominate the round cost (measured ~20x the compute)."""
            tp = target._dequant_params(tp)
            dp = draft._dequant_params(dp)

            def cond(state):
                return jnp.any(~state[4])

            def body(state):
                t_cache, d_cache, tok, lengths, done, produced, out_buf, rounds, acc_total = state
                t_cache, d_cache, tok, lengths, done, produced, out_buf, acc = spec_round(
                    tp, dp, t_cache, d_cache, tok, lengths, done, produced, out_buf
                )
                return (t_cache, d_cache, tok, lengths, done, produced, out_buf, rounds + 1, acc_total + acc)

            state = (t_cache, d_cache, tok, lengths, done, produced, out_buf, jnp.int32(0), jnp.int32(0))
            state = jax.lax.while_loop(cond, body, state)
            # final caches ride along (and are dropped by the caller) so the
            # donated inputs have outputs to alias with
            return state[6], state[7], state[8], state[0], state[1]

        return jax.jit(spec_loop, donate_argnums=(2, 3))

    # ------------------------------------------------------------------ generate

    def __call__(self, prompts: Sequence[Sequence[int]], *, seed: int = 0) -> np.ndarray:
        """Generate greedily; returns exactly what the target-only Generator would."""
        cfg = self.config
        if self._round_fn is None:
            self._round_fn = self._build_round()

        # prefill both models; extra cache headroom for the last round's overshoot
        n, tok0_t, _, (t_cache, _, lengths, done_t, _) = self._target._start(
            prompts, seed, extra_cache=self.gamma + 1
        )
        _, _, _, (d_cache, _, d_lengths, _, _) = self._draft._start(prompts, seed, extra_cache=self.gamma + 1)
        del d_lengths  # same values as lengths (same prompts)

        batch = int(tok0_t.shape[0])
        cap = cfg.max_new_tokens + self.gamma + 1
        out_buf = jnp.full((batch, cap), cfg.pad_id, jnp.int32)
        # the prompt-sampled token is emission #1 (same as Generator's tok0)
        out_buf = out_buf.at[:, 0].set(tok0_t)
        produced = jnp.ones((batch,), jnp.int32)
        done = done_t | (produced >= cfg.max_new_tokens)
        tok = tok0_t

        out_buf, rounds, accepted, _, _ = self._round_fn(
            self._target.params, self._draft.params,
            t_cache, d_cache, tok, lengths, done, produced, out_buf,
        )
        self.rounds += int(rounds)
        self.accepted_tokens += int(accepted)
        return np.asarray(out_buf)[:n, : cfg.max_new_tokens]
