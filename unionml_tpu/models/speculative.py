"""Speculative decoding: a small draft model proposes, the target verifies.

Standard draft-and-verify with distribution-level rejection sampling (the
Leviathan et al. scheme): per round the draft model decodes ``gamma`` tokens
from the decoding policy's distribution q (cheap — small model), then the
target model scores all ``gamma + 1`` positions in ONE cached forward (the
same HBM traffic as a single decode step at small batch: decode is
weight-bandwidth bound, so verifying gamma+1 tokens costs roughly one token).
Draft token x is accepted with probability ``min(1, p(x)/q(x))``; on the first
rejection the replacement is sampled from ``norm(max(p - q, 0))``, and when
everything accepts the target's own next-position distribution supplies a
bonus token — so every round emits 1..gamma+1 tokens and the output is
distributed **exactly** as target-only decoding (the draft can only change
speed, never the distribution). Greedy (``temperature == 0``) is the one-hot
special case: acceptance degenerates to argmax prefix matching and the output
is token-for-token the target-only greedy sequence — the oracle the tests pin.

TPU-native specifics:

- both models follow the shared cache contract (``unionml_tpu.models.generate``),
  so rollback is free: per-example ``lengths`` simply advance by each row's
  accepted count, and stale K/V beyond that is invisible (visibility mask is
  ``slot <= position``) and overwritten by later writes — no copying, no
  per-row cache surgery, and rows with different acceptance counts coexist in
  one batch;
- the whole post-prefill generation is ONE jitted ``lax.while_loop`` dispatch
  (per-round host round trips through a remote-TPU tunnel measured ~20x the
  round's compute); every shape is static and emitted tokens land in a device
  output buffer via per-row ``dynamic_update_slice`` at each row's ``produced``
  offset;
- eos handling matches :class:`~unionml_tpu.models.generate.Generator`: the
  first eos in a round truncates that row's emission and marks it done.

Sampled runs are NOT key-path-compatible with the plain Generator (they consume
randomness differently), so equality holds in distribution, not per seed —
tests/unit/test_speculative.py checks both: exact tokens for greedy, empirical
distribution closeness for sampling.

Routed-expert (MoE) targets: exactness additionally requires ample expert
capacity. Capacity is sized per routed group, and the ``[B, gamma+1]`` verify
forward routes ``gamma + 1`` tokens per row where target-only decode routes one
— under a tight ``capacity_factor`` a token can be capacity-dropped in the
verify but not in plain decode (or vice versa), perturbing its logits. Size
``capacity_factor`` for ``B * (gamma + 1)`` tokens when serving MoE targets
speculatively (the MoE test here uses an ample factor for this reason).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from unionml_tpu.models.generate import GenerationConfig, Generator, PrefixCache

__all__ = ["SpeculativeGenerator"]


class SpeculativeGenerator:
    """Greedy speculative decoding over a (target, draft) model pair.

    >>> spec = SpeculativeGenerator(target, target_params, draft, draft_params,
    ...                             GenerationConfig(max_new_tokens=128, temperature=0.0),
    ...                             gamma=4)
    >>> tokens = spec(prompts)          # == Generator(target, ...)(prompts), faster

    ``rounds`` / ``accepted_tokens`` counters expose the realized acceptance rate
    (``accepted_tokens / (rounds * gamma)``).
    """

    def __init__(
        self,
        target_module: Any,
        target_params: Any,
        draft_module: Any,
        draft_params: Any,
        config: GenerationConfig = GenerationConfig(temperature=0.0),
        *,
        gamma: int = 4,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        quantize: Optional[str] = None,
        quantize_draft: Optional[str] = None,
    ):
        import dataclasses

        # strip any attached DraftSpec: the internal Generators must decode
        # plainly (a draft-bearing config would recurse through the façade)
        config = dataclasses.replace(config, draft=None)
        # reuse the Generator machinery for prefill/placement/bucketing on both
        # models. ``quantize_draft`` ("int8") stores the draft quantized too;
        # None follows the serve-wide UNIONML_TPU_QUANTIZE default inside the
        # Generator — either way the draft only proposes and the target
        # decides, so the output law is untouched
        target = Generator(
            target_module, target_params, config,
            mesh=mesh, partition_rules=partition_rules, quantize=quantize,
        )
        self._init_state(
            target,
            Generator(
                draft_module, draft_params, target.config,
                mesh=mesh, partition_rules=partition_rules, quantize=quantize_draft,
            ),
            target.config,
            gamma,
        )

    def _init_state(self, target: Generator, draft: Generator, config: GenerationConfig, gamma: int) -> None:
        """The single construction body shared by ``__init__`` and
        :meth:`from_target` — any new field must be set here, so the two paths
        cannot drift."""
        if gamma < 1:
            raise ValueError("gamma must be >= 1")
        self.config = config
        self.gamma = int(gamma)
        self.rounds = 0
        self.accepted_tokens = 0
        self._target = target
        self._draft = draft
        self._round_fn = None
        # (weakref-to-prefix, draft_prefix) keyed on id(prefix); a finalizer
        # drops the entry when the PrefixCache is collected, so per-tenant
        # prefixes can't accumulate both models' KV forever, and the identity
        # check guards the window before a recycled id's finalizer runs
        self._draft_prefixes: dict = {}

    @classmethod
    def from_target(cls, target: Generator, draft: "Any") -> "SpeculativeGenerator":
        """Build around an EXISTING target :class:`Generator` (whose params are
        already quantized/sharded/placed) and a
        :class:`~unionml_tpu.models.generate.DraftSpec` — the path behind
        ``GenerationConfig(draft=...)`` on the Generator façade."""
        import dataclasses

        self = cls.__new__(cls)
        config = dataclasses.replace(target.config, draft=None)
        self._init_state(
            target,
            # the DraftSpec's quantize option ("int8", or None = the serve-wide
            # UNIONML_TPU_QUANTIZE default); target.config already resolved the
            # KV dtype, so both caches share one storage dtype
            Generator(
                draft.module, draft.params, config,
                mesh=target.mesh, partition_rules=draft.partition_rules,
                quantize=draft.quantize,
            ),
            config,
            draft.gamma,
        )
        return self

    # ------------------------------------------------------------------ round

    def _build_round(self):
        gamma = int(self.gamma)
        cfg = self.config
        target, draft = self._target, self._draft
        pad = jnp.int32(cfg.pad_id)
        eos = cfg.eos_id

        # reuse each generator's jit-side apply/head closures (same fns its own
        # prefill/decode compile) rather than re-deriving the forward here
        def draft_apply(p, tok, positions, cache):
            hidden, cache = draft._apply_fn(p, tok, positions, cache, None)
            return draft._head_fn(p, hidden), cache

        def target_apply(p, tok, positions, cache, token_mask):
            hidden, cache = target._apply_fn(p, tok, positions, cache, token_mask)
            return target._head_fn(p, hidden), cache

        from unionml_tpu.models.generate import filtered_logits, policy_probs

        greedy_mode = cfg.temperature == 0.0
        cs = cfg.constraints
        if cs is not None:
            # the same tables the target Generator placed on device: both
            # models' policies mask by the DFA state along the PROPOSED path,
            # so q and p are the constrained distributions and the rejection
            # law stays exact (q's support is within p's allowed set)
            cs_trans, cs_allowed = target._cs_trans, target._cs_allowed

        def spec_round(tp, dp, t_cache, d_cache, tok, lengths, done, produced, out_buf, key, budget, *st):
            key, draft_key, corr_key = jax.random.split(key, 3)
            accept_keys = jax.random.split(draft_key, gamma + 1)

            # --- draft: gamma policy-sampled steps (small-model cached decode) ---
            def draft_body(carry, step_key):
                cache, t, ln, *s = carry
                logits, cache = draft_apply(dp, t[:, None], ln[:, None], cache)
                lg = logits[:, 0]
                if cs is not None:
                    lg = jnp.where(cs_allowed[s[0]], lg, -jnp.inf)
                if greedy_mode:
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                else:
                    nxt = jax.random.categorical(step_key, filtered_logits(lg, cfg)).astype(jnp.int32)
                s_out = (s[0],) if cs is not None else ()
                s_next = (cs_trans[s[0], nxt],) if cs is not None else ()
                # emit the MASKED logits (q must be the constrained proposal
                # distribution) and the state BEFORE this position
                return (cache, nxt, ln + 1, *s_next), (nxt, lg, *s_out)

            carry_out, scanned = jax.lax.scan(
                draft_body, (d_cache, tok, lengths, *st), jax.random.split(accept_keys[gamma], gamma)
            )
            d_cache = carry_out[0]
            drafts, draft_logits = scanned[0], scanned[1]
            drafts = drafts.T  # [B, gamma]
            draft_logits = jnp.swapaxes(draft_logits, 0, 1)  # [B, gamma, V]
            if cs is not None:
                # states along the proposed path: st_ext[:, i] = state BEFORE
                # position i, for i in [0, gamma] (the bonus position included)
                st_ext = jnp.concatenate(
                    [jnp.swapaxes(scanned[2], 0, 1), carry_out[3][:, None]], axis=1
                )

            # --- draft-cache completeness: the scan fed [tok, drafts[:gamma-1]],
            # so drafts[gamma-1]'s K/V slot is never written; on an all-accept
            # round the next draft queries would attend to that zero-initialized
            # (visible) slot and acceptance would silently degrade as holes
            # accumulate. One extra headless feed fills it — for rows that
            # rejected earlier the slot is beyond their length (invisible stale
            # data, overwritten when they reach it), so the feed is always safe.
            _, d_cache = draft._apply_fn(
                dp, drafts[:, gamma - 1 :], (lengths + gamma)[:, None], d_cache, None
            )

            # --- target: score tok + all gamma drafts in one cached forward ---
            inputs = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B, gamma+1]
            positions = lengths[:, None] + jnp.arange(gamma + 1)[None]
            # routed decoders consume the mask per token: broadcast row-done
            # over the full [B, gamma+1] verify width
            verify_mask = jnp.broadcast_to((~done)[:, None], inputs.shape)
            logits, t_cache = target_apply(tp, inputs, positions, t_cache, verify_mask)
            if cs is not None:
                # target logits at position i masked by the state its row
                # reached after drafts[:i] — p becomes the constrained policy
                logits = jnp.where(cs_allowed[st_ext], logits, -jnp.inf)

            # --- rejection sampling against the policy distributions ---
            # (greedy is the one-hot special case: accept iff argmaxes agree, the
            # correction/bonus is the target argmax — exactly prefix matching)
            q = policy_probs(draft_logits, cfg)  # [B, gamma, V]
            p = policy_probs(logits, cfg)  # [B, gamma+1, V]
            batch = tok.shape[0]
            still = jnp.ones((batch,), bool)
            accepted = jnp.zeros((batch,), jnp.int32)
            for i in range(gamma):  # gamma is small and static; unrolled
                x = drafts[:, i : i + 1]
                px = jnp.take_along_axis(p[:, i], x, axis=-1)[:, 0]
                qx = jnp.take_along_axis(q[:, i], x, axis=-1)[:, 0]
                u = jax.random.uniform(accept_keys[i], (batch,))
                ok = u * qx < px  # u < p(x)/q(x), division-free
                accepted = accepted + (still & ok)
                still = still & ok
            # correction (first rejection) / bonus (all accepted) token: sample
            # from norm(max(p_a - q_a, 0)) — q beyond gamma is 0, so the bonus
            # case degenerates to sampling p_gamma directly
            p_at = jnp.take_along_axis(p, accepted[:, None, None], axis=1)[:, 0]  # [B, V]
            q_ext = jnp.concatenate([q, jnp.zeros_like(q[:, :1])], axis=1)
            q_at = jnp.take_along_axis(q_ext, accepted[:, None, None], axis=1)[:, 0]
            resid = jnp.maximum(p_at - q_at, 0.0)
            # float-edge guard: a rejected position has TV(p, q) > 0 by construction,
            # but under f32 the residual can still round to all-zeros
            resid = jnp.where(resid.sum(-1, keepdims=True) > 0, resid, p_at)
            correction = jax.random.categorical(corr_key, jnp.log(resid + 1e-30)).astype(jnp.int32)

            # emitted tokens this round: accepted drafts, then the correction
            idx = jnp.arange(gamma + 1)[None]
            drafts_ext = jnp.concatenate([drafts, jnp.full((batch, 1), pad)], axis=1)
            emit_mask = idx <= accepted[:, None]
            emitted = jnp.where(idx < accepted[:, None], drafts_ext, correction[:, None])
            emitted = jnp.where(emit_mask, emitted, pad)
            if eos is not None:
                is_eos = (emitted == eos) & emit_mask
                # truncate after the first eos: positions strictly beyond it emit pad
                seen_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
                emit_mask = emit_mask & (seen_before == 0)
                emitted = jnp.where(emit_mask, emitted, pad)
                row_hits_eos = is_eos.any(axis=1)
            else:
                row_hits_eos = jnp.zeros_like(done)
            emitted = jnp.where(done[:, None], pad, emitted)
            n_emit = jnp.where(done, 0, emit_mask.sum(axis=1))

            # clip to each row's generation budget (per-row: continuous batching
            # admits requests with different caps into one resident batch)
            room = jnp.maximum(budget - produced, 0)
            n_emit = jnp.minimum(n_emit, room)
            emitted = jnp.where(idx < n_emit[:, None], emitted, pad)

            out_buf = jax.vmap(
                lambda buf, row, start: jax.lax.dynamic_update_slice(buf, row, (start,))
            )(out_buf, emitted, produced)

            new_done = done | row_hits_eos | (produced + n_emit >= budget)
            # next round continues after the last emitted token; finished rows freeze
            tok = jnp.where(
                new_done, tok, jnp.take_along_axis(emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            )
            lengths = lengths + jnp.where(done, 0, n_emit)
            produced = produced + n_emit
            acc_count = jnp.where(done, 0, jnp.minimum(accepted, room)).sum()
            st_next = ()
            if cs is not None:
                # the next round's DFA state: advance past the LAST emitted
                # token. Emitted tokens are a prefix of the proposed path
                # (drafts[:accepted] then the correction), so the state before
                # position j is st_ext[:, j] regardless of eos/budget clipping.
                j = jnp.maximum(n_emit - 1, 0)
                st_before = jnp.take_along_axis(st_ext, j[:, None], axis=1)[:, 0]
                last_tok = jnp.take_along_axis(emitted, j[:, None], axis=1)[:, 0]
                st_next = (jnp.where(n_emit > 0, cs_trans[st_before, last_tok], st[0]),)
            return t_cache, d_cache, tok, lengths, new_done, produced, out_buf, acc_count, key, *st_next

        def spec_loop(tp, dp, state, floor, budget):
            """Post-prefill generation as ONE device-side while_loop — per-round
            host round trips through a remote-TPU tunnel would otherwise dominate
            the round cost (measured ~20x the compute). ``floor`` ([B] int32):
            keep rolling rounds while any unfinished row has produced fewer than
            its floor — ``__call__`` passes the budget (run to completion),
            :meth:`stream` and the continuous batcher pass ``produced + chunk``
            so tokens surface chunkwise with one device exit per chunk.
            ``budget`` ([B] int32) is each row's max_new_tokens cap."""
            tp = target._dequant_params(tp)
            dp = draft._dequant_params(dp)

            def cond(state):
                done_rows, produced_rows = state[4], state[5]
                return jnp.any(~done_rows & (produced_rows < floor))

            def body(state):
                t_cache, d_cache, tok, lengths, done, produced, out_buf, rounds, acc_total, key, *st = state
                t_cache, d_cache, tok, lengths, done, produced, out_buf, acc, key, *st = spec_round(
                    tp, dp, t_cache, d_cache, tok, lengths, done, produced, out_buf, key, budget, *st
                )
                return (t_cache, d_cache, tok, lengths, done, produced, out_buf, rounds + 1, acc_total + acc, key, *st)

            return jax.lax.while_loop(cond, body, state)

        # the whole state (caches, out_buf, counters) is donated and re-aliased
        # by the returned state, so repeated stream dispatches keep ONE copy in HBM
        return jax.jit(spec_loop, donate_argnums=(2,))

    # ------------------------------------------------------------------ generate

    def draft_prefix(self, prefix: PrefixCache) -> PrefixCache:
        """The DRAFT model's cache rows for a shared prefix: speculative
        decoding needs the system prompt resident in BOTH caches (the draft
        proposes conditioned on it, the target verifies conditioned on it), and
        their layer shapes differ — so the draft prefills the same token ids
        once here and the result is memoized per target-side PrefixCache."""
        import weakref

        entry = self._draft_prefixes.get(id(prefix))
        if entry is not None and entry[0]() is prefix:
            return entry[1]
        if prefix.tokens is None:
            raise ValueError(
                "prefix= with speculative decoding needs the prefix's token ids "
                "(build it with cache_prefix(...); hand-built PrefixCaches "
                "cannot be prefilled through the draft model)"
            )
        built = self._draft.cache_prefix(list(prefix.tokens))
        self._draft_prefixes[id(prefix)] = (weakref.ref(prefix), built)
        weakref.finalize(prefix, self._draft_prefixes.pop, id(prefix), None)
        return built

    def _start_state(
        self,
        prompts: Sequence[Sequence[int]],
        seed: int,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ):
        """Prefill both models and assemble the device-side loop state:
        ``(t_cache, d_cache, tok, lengths, done, produced, out_buf, rounds,
        accepted, key[, dfa_state])``. With ``prefix``, both models get their own
        prefix rows pasted and prefill only the suffix at a ``p0`` offset —
        lengths then include the prefix, so the round loop needs no changes.
        With constraints, the target's post-tok0 DFA state rides as the state's
        tail element."""
        cfg = self.config
        if self._round_fn is None:
            self._round_fn = self._build_round()
        # prefill both models; extra cache headroom for the last round's overshoot
        n, tok0_t, _, t_carry = self._target._start(
            prompts, seed, extra_cache=self.gamma + 1, prefix=prefix, constraint=constraint
        )
        t_cache, lengths, done_t = t_carry[0], t_carry[2], t_carry[3]
        _, _, _, d_carry = self._draft._start(
            prompts, seed, extra_cache=self.gamma + 1,
            prefix=self.draft_prefix(prefix) if prefix is not None else None,
            constraint=constraint,
        )
        d_cache = d_carry[0]  # d_carry's lengths equal `lengths` (same prompts/prefix)

        batch = int(tok0_t.shape[0])
        cap = cfg.max_new_tokens + self.gamma + 1
        out_buf = jnp.full((batch, cap), cfg.pad_id, jnp.int32)
        # the prompt-sampled token is emission #1 (same as Generator's tok0;
        # with constraints the target's _start already masked it)
        out_buf = out_buf.at[:, 0].set(tok0_t)
        produced = jnp.ones((batch,), jnp.int32)
        done = done_t | (produced >= cfg.max_new_tokens)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), 1)
        st = (t_carry[5],) if cfg.constraints is not None else ()
        return n, (
            t_cache, d_cache, tok0_t, lengths, done, produced, out_buf,
            jnp.int32(0), jnp.int32(0), key, *st,
        )

    def __call__(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        seed: int = 0,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ) -> np.ndarray:
        """Generate under the config's decoding policy; greedy output is exactly
        the target-only sequence, sampled output is target-distributed. With
        ``prefix`` (from the target's ``cache_prefix``), prompts are suffixes
        after the shared prefix in BOTH models. ``constraint`` (grammar ids into
        ``config.constraints``) masks both the draft's proposals and the
        target's verify by each row's DFA state — same output law as the
        constrained plain Generator."""
        cfg = self.config
        n, state = self._start_state(prompts, seed, prefix=prefix, constraint=constraint)
        budget = jnp.full(state[2].shape, cfg.max_new_tokens, jnp.int32)
        state = self._round_fn(self._target.params, self._draft.params, state, budget, budget)
        out_buf, rounds, accepted = state[6], state[7], state[8]
        self.rounds += int(rounds)
        self.accepted_tokens += int(accepted)
        return np.asarray(out_buf)[:n, : cfg.max_new_tokens]

    def stream(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        seed: int = 0,
        chunk_size: int = 16,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ):
        """Incremental speculative generation: yields a LIST of ``len(prompts)``
        1-D int32 arrays of newly materialized tokens per row (the first yield is
        each row's prompt-sampled token). Rows advance at round granularity
        (1..gamma+1 tokens per round), so per-yield chunks are RAGGED — unlike
        :meth:`Generator.stream`'s rectangular arrays. Token totals equal
        ``__call__`` for the same seed; each dispatch rolls rounds until every
        unfinished row has at least ``chunk_size`` more tokens, so streaming
        leaves the device once per chunk, not per round."""
        cfg = self.config
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        n, state = self._start_state(prompts, seed, prefix=prefix, constraint=constraint)
        prev = np.ones((n,), np.int64)
        first = np.asarray(state[6][:n, :1])  # one fetch, not one per row
        yield [first[i] for i in range(n)]
        budget = jnp.full(state[2].shape, cfg.max_new_tokens, jnp.int32)
        rounds = accepted = 0  # snapshots from the LAST SUCCESSFUL dispatch: the
        # in-flight state's buffers are donated, so reading it after a failed
        # dispatch would raise a secondary deleted-buffer error masking the cause
        try:
            while True:
                done_np = np.asarray(state[4])[:n]
                if bool(done_np.all()):
                    return
                # per-row floor: each unfinished row gains >= chunk_size tokens
                floor = jnp.minimum(state[5] + chunk_size, cfg.max_new_tokens)
                state = self._round_fn(
                    self._target.params, self._draft.params, state, floor, budget
                )
                out_np = np.asarray(state[6])
                prod_np = np.asarray(state[5])[:n]
                rounds, accepted = int(state[7]), int(state[8])
                yield [out_np[i, prev[i] : prod_np[i]] for i in range(n)]
                prev = prod_np.astype(np.int64)
        finally:
            self.rounds += rounds
            self.accepted_tokens += accepted
