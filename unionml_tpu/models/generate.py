"""Autoregressive generation engine: bucketed jitted prefill + one-compile decode loop.

The reference has no inference engine at all (it serves whatever ``model.predict``
does eagerly, unionml/fastapi.py:50-64); for the LLM family that leaves the flagship
model unservable. This module is the TPU-native answer, built on the same rules as
the serving layer's :class:`~unionml_tpu.serving.compile.CompiledPredictor`:

- **static shapes only**: prompts are padded to configured length buckets, the KV
  cache is a fixed ``[B, S_max, H_kv, D]`` ring of buffers, and the decode loop is a
  ``lax.scan`` over ``max_new_tokens`` steps — XLA sees ``len(buckets)`` prefill
  shapes and exactly one decode shape per (batch, cache_len);
- **per-example contiguous cache rows**: variable-length prompts are right-padded
  and each example's K/V rows are written at its own offsets
  (:func:`~unionml_tpu.models.layers._write_cache`), so no left-padding or position
  remapping is needed and RoPE positions equal cache slots;
- **cache donation**: prefill and every decode dispatch donate the cache buffers,
  so HBM holds one cache, not two;
- **mesh placement**: with a mesh + partition rules the params are placed sharded
  (e.g. megatron TP via :func:`~unionml_tpu.models.llama.llama_partition_rules`) and
  the cache is sharded batch-over-``data`` / heads-over-``model``; XLA inserts the
  collectives, identical tokens come out (tests/emulated/test_generate_tp.py).

Works with any flax module following the :class:`~unionml_tpu.models.llama.Llama`
cache contract: ``apply(vars, tokens, positions=[B,L], cache=...) -> (out, cache)``
(and ``return_hidden=True`` giving pre-head hidden states so prefill never
materializes a ``[B, P, vocab]`` logits tensor).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from unionml_tpu._logging import logger

__all__ = [
    "DraftSpec",
    "GenerationConfig",
    "Generator",
    "PrefixCache",
    "init_cache",
    "init_paged_cache",
    "sample_tokens",
]


@dataclasses.dataclass(frozen=True)
class DraftSpec:
    """A draft model for speculative decoding, attachable to
    :attr:`GenerationConfig.draft`: the :class:`Generator` façade then routes
    ``__call__``/``stream`` through a
    :class:`~unionml_tpu.models.speculative.SpeculativeGenerator` — same output
    law (greedy: token-exact; sampled: distribution-exact), fewer target
    dispatches per token. ``quantize`` ("int8") stores the DRAFT's weights
    quantized too — None follows the serve-wide ``UNIONML_TPU_QUANTIZE``
    default, exactly like the target Generator's own kwarg, so a quantized
    serving fleet drafts in int8 without a second knob. The output law is
    unchanged either way: the draft only proposes, the target decides."""

    module: Any
    params: Any
    gamma: int = 4
    partition_rules: Optional[Any] = None
    quantize: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Decoding knobs. ``temperature == 0`` means greedy (argmax) decoding;
    ``top_k``/``top_p`` filter the distribution before sampling."""

    max_new_tokens: int = 128
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    pad_id: int = 0
    #: prompt-length buckets; a batch's prompts are padded to the smallest bucket
    #: that fits, so XLA compiles at most ``len(prompt_buckets)`` prefill shapes
    prompt_buckets: Tuple[int, ...] = (64, 256, 1024)
    #: long-context prefill: process the prompt in fixed chunks of this many
    #: tokens through the cache instead of one [B, bucket] dispatch — activation
    #: memory stays O(B * chunk * dim) and ONE chunk shape covers every prompt
    #: length (the chunk fn compiles once, prompt buckets stop mattering for
    #: compile count). None = single-dispatch prefill.
    prefill_chunk: Optional[int] = None
    #: "int8" stores K/V rows symmetric-quantized per (position, head) with f32
    #: scales — long-context decode streams the cache every step, and int8
    #: halves those bytes (~0.4% logit drift on the shipped models' scale).
    #: None = compute dtype (bf16 on TPU).
    kv_cache_dtype: Optional[str] = None
    #: "ring" / "ulysses": run prefill SEQUENCE-PARALLEL over the mesh's
    #: ``sequence`` axis (the whole decoder under shard_map with the module's
    #: sequence-parallel attention), then assemble the KV cache from the sown
    #: per-layer K/V — prefill of a 100k-token prompt spreads across chips
    #: instead of living on one. Requires a mesh with a ``sequence`` axis;
    #: decode afterwards is the ordinary cached path.
    sp_prefill: Optional[str] = None
    #: attach a :class:`DraftSpec` to decode speculatively through the same
    #: Generator façade (excluded from equality/repr — it carries param trees)
    draft: Optional["DraftSpec"] = dataclasses.field(default=None, compare=False, repr=False)
    #: a :class:`~unionml_tpu.models.structured.ConstraintSet` enabling
    #: grammar-constrained decoding: pass ``constraint=`` (grammar ids) to
    #: :meth:`Generator.__call__` / :meth:`Generator.stream` and each row's
    #: logits are masked by its grammar's token-DFA inside the decode scan.
    #: Excluded from equality/repr — it carries the DFA tables.
    constraints: Optional[Any] = dataclasses.field(default=None, compare=False, repr=False)
    #: keep only tokens whose probability is at least ``min_p`` times the most
    #: likely token's (applied after temperature, before top-k/top-p) — an
    #: adaptive nucleus: permissive when the model is unsure, sharp when it is
    #: confident. 0.0 disables. Appended last so existing positional
    #: construction is unaffected.
    min_p: float = 0.0


def chunk_aligned(length: int, chunk: int) -> int:
    """Round ``length`` up to a multiple of ``chunk`` — the width a chunked
    prefill actually pads to and writes. Every cache sized to receive a chunked
    prefill must use THIS width (not the raw bucket), so the sizing rule lives
    in one place (round 3 had a hand-copied variant drift and clamp-corrupt
    cache rows in continuous batching)."""
    return -(-length // chunk) * chunk


def init_cache(config: Any, batch: int, cache_len: int, kv_dtype: Optional[str] = None) -> Tuple[Any, ...]:
    """Zeroed per-layer KV buffers for a decoder with ``config.n_layers`` layers,
    ``config.n_kv_heads`` KV heads and head_dim ``dim // n_heads``, stored in the
    compute dtype (bf16 on TPU — halves cache HBM vs f32). ``kv_dtype="int8"``
    adds per-(position, head) scale planes and stores values int8 (see
    :class:`~unionml_tpu.models.layers.Attention`'s cached branch)."""
    head_dim = config.dim // config.n_heads
    shape = (batch, cache_len, config.n_kv_heads, head_dim)
    if kv_dtype == "int8":
        scale_shape = (batch, cache_len, config.n_kv_heads, 1)
        return tuple(
            {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(scale_shape, jnp.float32),
                "v_scale": jnp.zeros(scale_shape, jnp.float32),
            }
            for _ in range(config.n_layers)
        )
    if kv_dtype is not None:
        raise ValueError(f"unsupported kv_cache_dtype {kv_dtype!r}; expected None or 'int8'")
    return tuple(
        {"k": jnp.zeros(shape, config.dtype), "v": jnp.zeros(shape, config.dtype)}
        for _ in range(config.n_layers)
    )


def init_paged_cache(
    config: Any,
    slots: int,
    n_blocks: int,
    block_size: int,
    max_blocks: int,
    kv_dtype: Optional[str] = None,
    *,
    fill_block: int,
) -> Tuple[Any, ...]:
    """Per-layer PAGED KV buffers: a shared pool of ``n_blocks`` blocks of
    ``block_size`` positions plus a ``[slots, max_blocks]`` block table
    initialized to ``fill_block``. Pools are HEADS-MAJOR
    (``[H_kv, n_blocks, block_size, D]``) — the layout
    ``jax.experimental.pallas.ops.tpu.paged_attention`` consumes directly, so
    the kernel path needs no transpose.
    ``fill_block`` is REQUIRED and must be a reserved scratch block (allocate
    ``n_blocks = real + 1`` and pass ``fill_block = real``, as
    ``ContinuousBatcher._init_carry`` does): free and finished slots keep
    issuing one ride-along K/V write per step through their table row, and a
    default of 0 would scatter that garbage into live block 0. The layer dicts
    follow :func:`init_cache`'s int8 convention, with the table riding in each
    layer (same values; a few hundred bytes). See
    :meth:`unionml_tpu.models.layers.Attention._paged_cached_attention` for the
    read/write contract; HBM scales with the pool, not slots x worst-case."""
    head_dim = config.dim // config.n_heads
    shape = (config.n_kv_heads, n_blocks, block_size, head_dim)
    # one table PER layer (same values): the cache is donated through admission
    # and decode, and donating an array aliased across layers is an XLA error
    # ("donate the same buffer twice"); the duplication is a few hundred bytes
    table = lambda: jnp.full((slots, max_blocks), fill_block, jnp.int32)  # noqa: E731
    if kv_dtype == "int8":
        scale_shape = (config.n_kv_heads, n_blocks, block_size, 1)
        return tuple(
            {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(scale_shape, jnp.float32),
                "v_scale": jnp.zeros(scale_shape, jnp.float32),
                "table": table(),
            }
            for _ in range(config.n_layers)
        )
    if kv_dtype is not None:
        raise ValueError(f"unsupported kv_cache_dtype {kv_dtype!r}; expected None or 'int8'")
    return tuple(
        {"k": jnp.zeros(shape, config.dtype), "v": jnp.zeros(shape, config.dtype), "table": table()}
        for _ in range(config.n_layers)
    )


def _paste_prefix_rows(cache: Any, prefix_layers: Any) -> Any:
    """Broadcast a :class:`PrefixCache`'s ``[1, p0, ...]`` K/V rows into slots
    ``[0, p0)`` of every row of a freshly allocated cache. Jitted (donating the
    cache) so the paste is one fused dispatch, not 2 * n_layers eager ops."""

    def paste(buf: jax.Array, pre: jax.Array) -> jax.Array:
        pre = jnp.broadcast_to(pre.astype(buf.dtype), (buf.shape[0],) + pre.shape[1:])
        return jax.lax.dynamic_update_slice(buf, pre, (0,) * buf.ndim)

    return jax.tree_util.tree_map(paste, cache, prefix_layers)


_paste_prefix_rows = jax.jit(_paste_prefix_rows, donate_argnums=(0,))


def gather_paged_rows(pool_cache: Any, blocks_row: jax.Array, width: int) -> Tuple[Any, ...]:
    """Materialize a dense ``[1, width, H_kv, last]`` cache row from a PAGED
    pool (:func:`init_paged_cache`): position ``pos`` reads block
    ``blocks_row[pos // block_size]`` at offset ``pos % block_size`` — the
    exact inverse of the admission scatter, so a row gathered from cached
    blocks is bit-identical to the row that was scattered in. The serving
    engine's radix prefix cache uses this to seed an admission's prefill row
    from arbitrary cached block runs (positions past the cached region gather
    scratch/garbage, which the suffix prefill overwrites before anything can
    attend to it). ``width`` is static (one compile per engine: callers pass
    their fixed ``cache_len``); the per-layer ``table`` entries ride along
    unused."""
    block_size = pool_cache[0]["k"].shape[2]  # pools are heads-major [H, NB, bs, last]
    pos = jnp.arange(width)
    blk, off = blocks_row[pos // block_size], pos % block_size
    rows = []
    for layer in pool_cache:
        row = {}
        for name in layer:
            if name == "table":
                continue
            # [H, width, last] -> [1, width, H, last], the dense-row layout
            row[name] = jnp.swapaxes(layer[name][:, blk, off], 0, 1)[None]
        rows.append(row)
    return tuple(rows)


def _quantized_shardings(qparams: Any, shardings: Any, mesh: Any) -> Any:
    """Expand a (pre-quantization) sharding tree to match a quantized params tree:
    each :class:`~unionml_tpu.ops.quant.QuantizedTensor` leaf becomes a
    QuantizedTensor of shardings — the int8 values take the kernel's resolved
    sharding, and the per-channel ``scale`` keeps only the axes on its non-unit
    dims (size-1 reduction dims cannot carry a mesh axis)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from unionml_tpu.ops.quant import QuantizedTensor

    def fix(leaf: Any, sharding: Any) -> Any:
        if not isinstance(leaf, QuantizedTensor):
            return sharding
        spec = tuple(sharding.spec) + (None,) * (len(leaf.scale.shape) - len(tuple(sharding.spec)))
        scale_spec = tuple(None if dim == 1 else axis for dim, axis in zip(leaf.scale.shape, spec))
        return QuantizedTensor(q=sharding, scale=NamedSharding(mesh, P(*scale_spec)))

    return jax.tree_util.tree_map(
        fix, qparams, shardings, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def filtered_logits(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    """Apply the decoding policy's temperature/top-k/top-p filters to ``[..., V]``
    logits (masked entries become -inf). ``softmax`` of the result IS the policy's
    sampling distribution — speculative sampling rejects against exactly this."""
    logits = logits / config.temperature
    if config.min_p > 0.0:
        # prob(x) >= min_p * prob(argmax)  <=>  logit(x) >= max_logit + log(min_p)
        # (softmax normalizers cancel), so the filter needs no softmax at all
        cutoff = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(config.min_p)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    if config.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -config.top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if config.top_p < 1.0:
        sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        # keep the smallest prefix whose mass reaches top_p; the lowest kept logit
        # becomes the cutoff mapped back onto the unsorted axis
        dropped = exclusive_cum >= config.top_p
        min_kept = jnp.min(jnp.where(dropped, jnp.inf, sorted_desc), axis=-1, keepdims=True)
        logits = jnp.where(logits < min_kept, -jnp.inf, logits)
    return logits


def policy_probs(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    """The decoding policy as an explicit distribution over ``[..., V]`` — a
    one-hot argmax for greedy, else softmax of :func:`filtered_logits`."""
    if config.temperature == 0.0:
        return jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=jnp.float32)
    return jax.nn.softmax(filtered_logits(logits.astype(jnp.float32), config), axis=-1)


def sample_tokens(logits: jax.Array, key: jax.Array, config: GenerationConfig) -> jax.Array:
    """Sample next tokens from ``logits [B, V]`` under the config's decoding policy."""
    if config.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, filtered_logits(logits, config)).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class PrefixCache:
    """Precomputed K/V rows for a shared prompt prefix (a system prompt): built
    once by :meth:`Generator.cache_prefix`, reused by every request that passes
    it — the prefix's prefill cost is paid once, not per call."""

    layers: Tuple[Any, ...]  # per-layer cache leaves trimmed to [1, length, ...]
    length: int
    #: the prefix's token ids — kept so engines with a SECOND model (speculative
    #: decoding's draft) can prefill the same prefix through it; None for
    #: hand-built caches, which then can't compose with a draft
    tokens: Optional[Tuple[int, ...]] = None


class Generator:
    """Batch text generation over a cached decoder.

    >>> gen = Generator(module, params, GenerationConfig(max_new_tokens=64))
    >>> tokens = gen([[1, 5, 9], [3, 3]], seed=0)   # [2, 64] int32

    ``prefill_traces`` / ``decode_traces`` count XLA traces; within the configured
    prompt buckets and a fixed batch size they stay at (<= len(buckets), 1).
    """

    def __init__(
        self,
        module: Any,
        params: Any,
        config: GenerationConfig = GenerationConfig(),
        *,
        mesh: Optional[Any] = None,
        partition_rules: Optional[Any] = None,
        quantize: Optional[str] = None,
    ):
        from unionml_tpu.defaults import serve_kv_cache_dtype, serve_quantize

        # serve-time quantization defaults (the --dp-replicas early-export
        # contract): an unset kwarg falls back to the serve CLI's
        # UNIONML_TPU_QUANTIZE export, and an unset config.kv_cache_dtype to
        # UNIONML_TPU_KV_CACHE_DTYPE — so `serve --quantize int8
        # --kv-cache-dtype int8` quantizes app-built Generators with zero app
        # code changes. Explicit values always win; with the env unset both
        # resolutions are identity and nothing changes.
        if quantize is None:
            quantize = serve_quantize()
        if config.kv_cache_dtype is None:
            env_kv = serve_kv_cache_dtype()
            if env_kv is not None:
                config = dataclasses.replace(config, kv_cache_dtype=env_kv)
        if config.kv_cache_dtype not in (None, "int8"):
            # init_cache would raise the same at first use; failing at
            # construction keeps the error next to the config that caused it
            raise ValueError(
                f"unsupported kv_cache_dtype {config.kv_cache_dtype!r}; expected None or 'int8'"
            )
        self.module = module
        self.config = config
        self.mesh = mesh
        #: retained so engines re-hosting these weights (the serving replica
        #: layer re-placing params onto per-replica submeshes) can rebuild a
        #: Generator with identical sharding/quantization choices
        self.partition_rules = partition_rules
        self.quantize = quantize
        self.prefill_traces = 0
        self.decode_traces = 0
        compute_dtype = getattr(getattr(module, "config", None), "dtype", jnp.bfloat16)

        if quantize not in (None, "int8"):
            raise ValueError(f"unsupported quantize mode {quantize!r}; expected None or 'int8'")

        from unionml_tpu.parallel.sharding import combine_fsdp_tp, shard_pytree, unbox_partitioned

        # resolve shardings from the still-boxed tree so nn.Partitioned metadata
        # keeps its precedence over regex rules / inferred FSDP, then unbox (the
        # sharding tree matches the unboxed structure)
        shardings = combine_fsdp_tp(params, mesh, partition_rules) if mesh is not None else None
        params = unbox_partitioned(params)
        if quantize == "int8":
            from unionml_tpu.ops.quant import quantize_params

            params = quantize_params(params)
            if shardings is not None:
                shardings = _quantized_shardings(params, shardings, mesh)
        if shardings is not None:
            params = shard_pytree(params, shardings)
        self.params = params

        if quantize == "int8":
            from unionml_tpu.ops.quant import dequantize_tree

            # called inside jit (and inside the decode scan body): XLA fuses the
            # int8->compute convert into consumers; int8 is what crosses HBM
            dequant = lambda p: dequantize_tree(p, dtype=compute_dtype)  # noqa: E731
        else:
            dequant = lambda p: p  # noqa: E731
        self._dequant_params = dequant  # for engines composing on top (speculative)

        cs = config.constraints
        if cs is not None:
            # the tables ride to the device once and are MEMOIZED on the set —
            # plain/target/draft engines over one ConstraintSet share a single
            # copy; inside the jitted step the constraint is two gathers and a
            # where (see models/structured.py). With config.draft also set, the
            # speculative engine threads the same per-row DFA state along the
            # draft path (speculative.py).
            self._cs_trans, self._cs_allowed = cs.device_tables()
        self._cs = cs

        def constrain(logits: jax.Array, cstate: tuple) -> jax.Array:
            """Mask ``[..., V]`` logits by each row's DFA state (``cstate`` is
            the variadic tail — empty when the generator is unconstrained, so
            every unconstrained signature and carry layout stays exactly as
            before)."""
            if cs is None:
                return logits
            return jnp.where(self._cs_allowed[cstate[0]], logits, -jnp.inf)

        self._constrain = constrain  # shared by sp_prefill and beam search

        def apply(p: Any, tokens: jax.Array, positions: jax.Array, cache: Any, token_mask: Any):
            hidden, cache = module.apply(
                {"params": p},
                tokens,
                positions=positions,
                return_hidden=True,
                cache=cache,
                token_mask=token_mask,
            )
            return hidden, cache

        def head(p: Any, hidden: jax.Array) -> jax.Array:
            kernel = p["lm_head"]["kernel"]
            return (hidden @ kernel.astype(hidden.dtype)).astype(jnp.float32)

        def prefill(p, tokens, lengths, cache, key, row_valid, *cstate):
            self.prefill_traces += 1
            p = dequant(p)
            batch, prompt_len = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(prompt_len)[None], (batch, prompt_len))
            # padding (right-pad columns and synthetic batch rows) must not claim
            # routed-expert capacity — mask it out of the token stream
            token_mask = (jnp.arange(prompt_len)[None] < lengths[:, None]) & row_valid[:, None]
            hidden, cache = apply(p, tokens, positions, cache, token_mask)
            last = jnp.take_along_axis(hidden, (lengths - 1)[:, None, None], axis=1)[:, 0]
            tok0 = sample_tokens(constrain(head(p, last), cstate), key, config)
            return tok0, cache, last.astype(jnp.float32)

        def prefill_chunk(p, tokens, start, lengths, cache, row_valid):
            """One chunk of a long-context prefill: columns [start, start+C) of the
            padded prompt flow through the cache (attention sees all previously
            written slots). Also extracts the hidden row of each example's last
            real token if it falls inside this chunk."""
            self.prefill_traces += 1
            p = dequant(p)
            batch, chunk = tokens.shape
            positions = start + jnp.broadcast_to(jnp.arange(chunk)[None], (batch, chunk))
            token_mask = (positions < lengths[:, None]) & row_valid[:, None]
            hidden, cache = apply(p, tokens, positions, cache, token_mask)
            sel = positions == (lengths - 1)[:, None]  # at most one true column per row
            chunk_last = jnp.einsum("blc,bl->bc", hidden.astype(jnp.float32), sel.astype(jnp.float32))
            return chunk_last, sel.any(axis=1), cache

        def first_token(p, last, key, *cstate):
            """Sample the first generated token from accumulated last-row hiddens
            (chunked-prefill epilogue; everything but lm_head is DCE'd)."""
            p = dequant(p)
            return sample_tokens(constrain(head(p, last.astype(compute_dtype)), cstate), key, config)

        def decode_steps(p, cache, tok, lengths, done, key, *cstate, steps: int):
            """Roll ``steps`` decode steps from the carry; returns the new tokens
            ``[B, steps]``, each sampled token's log-probability ``[B, steps]``
            f32 (under the constrained policy distribution — the OpenAI
            ``logprobs`` surface reads these; done rows report 0.0), and the
            advanced carry. One ``lax.scan`` compile per distinct ``steps``
            value — __call__ always uses max_new_tokens - 1 and stream() a
            fixed chunk size, so the trace set stays tiny. With constraints the
            carry gains each row's DFA state as its tail element; ``steps`` is
            keyword-only so both carry layouts share this signature."""
            self.decode_traces += 1
            eos = config.eos_id

            def body(carry, _):
                cache, tok, lengths, done, key, *cst = carry
                key, sub = jax.random.split(key)
                ps = dequant(p)  # per-step so int8, not bf16, is the steady-state HBM read
                positions = lengths[:, None]  # each example's next free cache slot
                hidden, cache = apply(ps, tok[:, None], positions, cache, (~done)[:, None])
                logits = constrain(head(ps, hidden[:, 0]), cst)
                nxt = sample_tokens(logits, sub, config)
                # the chosen token's logprob rides along (one gather + one
                # logsumexp over logits the head already materialized — noise
                # next to the matmul); done rows' pad "samples" report 0.0
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=1
                )[:, 0]
                lp = jnp.where(done, jnp.float32(0.0), lp)
                if cs is not None:
                    # done rows hold their state (their sampled token is a pad)
                    cst = (jnp.where(done, cst[0], self._cs_trans[cst[0], nxt]),)
                nxt = jnp.where(done, jnp.int32(config.pad_id), nxt)
                lengths = lengths + jnp.where(done, 0, 1)
                if eos is not None:
                    done = done | (nxt == eos)
                return (cache, nxt, lengths, done, key, *cst), (nxt, lp)

            carry, (toks, lps) = jax.lax.scan(
                body, (cache, tok, lengths, done, key, *cstate), None, length=steps
            )
            # the advanced carry (incl. cache) is returned so the donated input
            # buffers have outputs to alias with — one cache in HBM throughout
            return toks.T, lps.T, carry

        # donate the cache through both stages: one cache lives in HBM, not two
        self._prefill = jax.jit(prefill, donate_argnums=(3,))
        self._prefill_chunk = jax.jit(prefill_chunk, donate_argnums=(4,))
        self._first_token = jax.jit(first_token)
        self._decode = jax.jit(decode_steps, static_argnames=("steps",), donate_argnums=(1,))
        self._apply_fn = apply  # for engines composing on top (beam search)
        self._head_fn = head
        self._beam_fns: dict = {}
        self._sp_prefill_fn = None
        self._spec_engine = None  # lazily built when config.draft is set
        #: AOT program store (serving/aot.py): set by :meth:`enable_aot`, after
        #: which the jitted programs above resolve load-before-compile
        self._aot_store = None

    # ------------------------------------------------------------------ AOT preload

    def _aot_context(self) -> dict:
        """The key parts that pin a serialized executable to THIS generator's
        programs: module architecture, generation config (kv dtype, buckets,
        sampling law — all compiled into the programs), quantization mode,
        mesh topology, and — because grammar tables are traced in as
        constants — a digest of the constraint set's tables."""
        import hashlib as _hashlib

        from unionml_tpu.serving.aot import mesh_context

        ctx = {
            "module": type(self.module).__name__,
            "module_config": repr(getattr(self.module, "config", None)),
            "generation_config": repr(self.config),
            "quantize": self.quantize,
            # bumped when a program's OUTPUT signature changes (the decode
            # scan gained a logprobs output): stale serialized executables
            # from an older layout must miss and recompile, not load
            "program_abi": "decode-logprobs-v2",
            **mesh_context(self.mesh),
        }
        if self._cs is not None:
            digest = _hashlib.sha256()
            digest.update(np.asarray(self._cs_trans).tobytes())
            digest.update(np.asarray(self._cs_allowed).tobytes())
            ctx["constraints"] = digest.hexdigest()
        return ctx

    def enable_aot(self, store: Any) -> "Generator":
        """Route this generator's jitted programs (``_prefill`` per bucket,
        ``_prefill_chunk``, ``_first_token``, ``_decode``, and the lazily
        built sequence-parallel prefill) through an AOT
        :class:`~unionml_tpu.serving.aot.ProgramStore`: every distinct call
        signature resolves load-before-compile, and every compile that does
        happen is serialized back so the next cold process loads it. Tokens
        are bit-identical either way — a loaded executable IS the program a
        fresh compile would produce. Idempotent; ``None`` is a no-op."""
        if store is None or self._aot_store is not None:
            return self
        from unionml_tpu.serving.aot import AOTFunction

        ctx = self._aot_context()
        self._aot_store = store
        self._prefill = AOTFunction(self._prefill, "prefill", store, ctx)
        self._prefill_chunk = AOTFunction(self._prefill_chunk, "prefill_chunk", store, ctx)
        self._first_token = AOTFunction(self._first_token, "first_token", store, ctx)
        self._decode = AOTFunction(
            self._decode, "decode", store, ctx, static_argnames=("steps",)
        )
        return self

    def warmup(self) -> "Generator":
        """Resolve the batch-1 prefill program for every configured prompt
        bucket plus one decode scan — through the AOT store when
        :meth:`enable_aot` armed one (load-before-compile; a populated store
        makes this load-bound), as a plain compile otherwise. The serving
        engines have their own richer warmup; this is the standalone
        ``Generator`` analog the serverless batch path and notebooks use."""
        cfg = self.config
        vocab = int(getattr(self.module.config, "vocab_size", 2))
        tok = 1 % max(vocab, 1)
        decoded = False
        for bucket in sorted(set(cfg.prompt_buckets)):
            _, _, _, carry = self._start([[tok] * bucket], 0)
            if not decoded and cfg.max_new_tokens >= 2:
                # one scan covers every bucket: the cache width is shared
                # (cache_len keys off the WIDEST bucket), so decode is one
                # program regardless of which bucket prefilled the carry
                self._decode(self.params, *carry, steps=cfg.max_new_tokens - 1)
                decoded = True
        return self

    def _speculative(self):
        """The internal speculative engine for ``config.draft`` — reuses THIS
        generator (params already quantized/placed) as the verify target."""
        if self._spec_engine is None:
            from unionml_tpu.models.speculative import SpeculativeGenerator

            self._spec_engine = SpeculativeGenerator.from_target(self, self.config.draft)
        return self._spec_engine

    # ------------------------------------------------------------------ helpers

    def _build_sp_prefill(self):
        """Sequence-parallel prefill: the decoder runs under shard_map with its
        ring/ulysses attention over the ``sequence`` axis, per-layer post-RoPE
        K/V are sown out, and shard_map's output stitching yields the global
        K/V to write into the cache. One jit per prompt-bucket shape."""
        import dataclasses as _dc

        from jax.sharding import PartitionSpec as P

        from unionml_tpu.models.layers import quantize_kv_rows

        cfg = self.config
        mesh = self.mesh
        sp_module = type(self.module)(_dc.replace(self.module.config, attention_impl=cfg.sp_prefill))
        n_layers = self.module.config.n_layers
        compute_dtype = getattr(self.module.config, "dtype", jnp.bfloat16)
        data_axes = tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1) or None

        def local_fwd(tokens_local, mask_local, p):
            seq_idx = jax.lax.axis_index("sequence")
            local_len = tokens_local.shape[1]
            positions = seq_idx * local_len + jnp.arange(local_len)
            hidden, variables = sp_module.apply(
                {"params": p},
                tokens_local,
                positions,
                return_hidden=True,
                token_mask=mask_local,
                mutable=["kvs"],
            )
            kvs = variables["kvs"]
            ks = tuple(kvs[f"layer_{i}"]["attn"]["k"][0] for i in range(n_layers))
            vs = tuple(kvs[f"layer_{i}"]["attn"]["v"][0] for i in range(n_layers))
            return hidden, ks, vs

        try:
            from jax import shard_map
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

        tok_spec = P(data_axes, "sequence")
        act_spec = P(data_axes, "sequence", None)
        kv_spec = P(data_axes, "sequence", None, None)
        out_specs = (act_spec, (kv_spec,) * n_layers, (kv_spec,) * n_layers)
        in_specs = (tok_spec, tok_spec, P())
        try:
            wrapped = shard_map(
                local_fwd, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
            )
        except TypeError:  # older API spells the replication-check flag differently
            wrapped = shard_map(
                local_fwd, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
            )

        def sp_prefill(p, tokens, lengths, cache, key, row_valid, *cstate):
            self.prefill_traces += 1
            p = self._dequant_params(p)
            # pad columns and synthetic batch rows must not claim routed-expert
            # capacity — same contract as the dense prefill's token_mask
            token_mask = (jnp.arange(tokens.shape[1])[None] < lengths[:, None]) & row_valid[:, None]
            hidden, ks, vs = wrapped(tokens, token_mask, p)
            new_cache = []
            for i in range(n_layers):
                layer = cache[i]
                if "k_scale" in layer:
                    kq, k_scale = quantize_kv_rows(ks[i])
                    vq, v_scale = quantize_kv_rows(vs[i])
                    layer = {
                        "k": jax.lax.dynamic_update_slice(layer["k"], kq, (0, 0, 0, 0)),
                        "v": jax.lax.dynamic_update_slice(layer["v"], vq, (0, 0, 0, 0)),
                        "k_scale": jax.lax.dynamic_update_slice(layer["k_scale"], k_scale, (0, 0, 0, 0)),
                        "v_scale": jax.lax.dynamic_update_slice(layer["v_scale"], v_scale, (0, 0, 0, 0)),
                    }
                else:
                    layer = {
                        "k": jax.lax.dynamic_update_slice(
                            layer["k"], ks[i].astype(layer["k"].dtype), (0, 0, 0, 0)
                        ),
                        "v": jax.lax.dynamic_update_slice(
                            layer["v"], vs[i].astype(layer["v"].dtype), (0, 0, 0, 0)
                        ),
                    }
                new_cache.append(layer)
            last = jnp.take_along_axis(hidden, (lengths - 1)[:, None, None], axis=1)[:, 0]
            logits = self._constrain(self._head_fn(p, last.astype(compute_dtype)), cstate)
            tok0 = sample_tokens(logits, key, cfg)
            return tok0, tuple(new_cache), last.astype(jnp.float32)

        jitted = jax.jit(sp_prefill, donate_argnums=(3,))
        if self._aot_store is not None:
            from unionml_tpu.serving.aot import AOTFunction

            return AOTFunction(jitted, "sp_prefill", self._aot_store, self._aot_context())
        return jitted

    def _bucket(self, max_prompt: int) -> int:
        for b in sorted(self.config.prompt_buckets):
            if b >= max_prompt:
                return b
        # oversized prompt: one extra trace at the next multiple of 64, logged
        bucket = int(math.ceil(max_prompt / 64) * 64)
        logger.info(f"prompt length {max_prompt} exceeds configured buckets; padding to {bucket}")
        return bucket

    def _place_cache(self, cache: Any) -> Any:
        if self.mesh is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as P

        def spec(a: jax.Array) -> NamedSharding:
            data = "data" if "data" in self.mesh.axis_names else None
            model = "model" if "model" in self.mesh.axis_names else None
            if model is not None and a.shape[2] % self.mesh.shape["model"] != 0:
                model = None  # KV heads not divisible by the model axis: replicate heads
            return NamedSharding(self.mesh, P(data, None, model, None))

        return jax.tree_util.tree_map(lambda a: jax.device_put(a, spec(a)), cache)

    def _place_paged_cache(self, cache: Any) -> Any:
        """Mesh placement for a PAGED pool (:func:`init_paged_cache`): the
        heads-major ``[H_kv, n_blocks, block_size, D]`` pools shard their head
        dim over the model axis — the same axis the dense ``[B, L, H, D]``
        cache shards in :meth:`_place_cache` — and the ``[slots, max_blocks]``
        block tables replicate (every shard needs the full table to gather its
        own heads' blocks)."""
        if self.mesh is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as P

        def spec(a: jax.Array) -> NamedSharding:
            model = "model" if "model" in self.mesh.axis_names else None
            if a.ndim != 4 or (model is not None and a.shape[0] % self.mesh.shape["model"] != 0):
                model = None  # tables, or KV heads indivisible by the axis: replicate
            return NamedSharding(self.mesh, P(model))

        return jax.tree_util.tree_map(lambda a: jax.device_put(a, spec(a)), cache)

    # ------------------------------------------------------------------ generate

    def cache_prefix(self, prefix_tokens: Sequence[int]) -> PrefixCache:
        """Prefill a shared prompt prefix once and return its K/V rows for reuse:
        pass the result as ``prefix=`` to :meth:`__call__` / :meth:`stream` and
        only the per-request suffix is prefilled — the system-prompt cost is paid
        here, not per request."""
        p0 = len(prefix_tokens)
        if p0 == 0:
            raise ValueError("prefix_tokens must be non-empty")
        _, _, _, carry = self._start([list(prefix_tokens)], 0)
        cache = carry[0]
        return PrefixCache(
            layers=jax.tree_util.tree_map(lambda c: c[:1, :p0], cache),
            length=p0,
            tokens=tuple(int(t) for t in prefix_tokens),
        )

    def _start(
        self,
        prompts: Sequence[Sequence[int]],
        seed: int,
        extra_cache: int = 0,
        batch_override: Optional[int] = None,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ):
        """Shared prefill setup: pad/bucket the prompts, allocate + place the cache,
        run prefill, and return the first sampled token, the last-token hidden
        states, and the decode carry. ``batch_override`` pins the padded batch
        exactly (beam search needs batch == groups * num_beams). With ``prefix``,
        the cached prefix rows are pasted into every row's cache and only the
        suffix is prefilled (through the chunked path, which takes a start
        offset). ``constraint`` (an int or one int per prompt) selects each row's
        grammar from ``config.constraints``; rows then start at that grammar's
        DFA start state and the carry gains the per-row state as its tail."""
        cfg = self.config
        if constraint is not None and self._cs is None:
            raise ValueError("constraint= requires GenerationConfig.constraints to be set")
        n = len(prompts)
        if prefix is not None and any(len(p) == 0 for p in prompts):
            # an empty suffix would silently condition on prefix + [pad_id]
            # (lengths are clamped to >= 1 below); bare continuation from a
            # prefix would need the prefix's last-token hidden, which
            # cache_prefix does not keep
            raise ValueError("prompts must be non-empty when prefix= is given")
        lengths = np.array([max(len(p), 1) for p in prompts], np.int32)
        bucket = self._bucket(int(lengths.max()))
        if batch_override is not None:
            if batch_override < n:
                raise ValueError(f"batch_override {batch_override} < {n} prompts")
            batch = batch_override
        else:
            # pad the batch to a power of two so XLA sees few batch shapes — and to
            # a multiple of the mesh's data axis so the cache batch dim shards evenly
            batch = 1 << max(0, (n - 1).bit_length())
            if self.mesh is not None and "data" in self.mesh.axis_names:
                data = int(self.mesh.shape["data"])
                batch = int(math.ceil(batch / data) * data)
        tokens = np.full((batch, bucket), cfg.pad_id, np.int32)
        for i, p in enumerate(prompts):
            tokens[i, : len(p)] = np.asarray(p, np.int32)
        all_lengths = np.ones((batch,), np.int32)
        all_lengths[:n] = lengths

        cstate: tuple = ()
        if self._cs is not None:
            cstate = (jnp.asarray(self._cs.start_states(self._grammar_ids(constraint, n, batch))),)

        sp = (
            cfg.sp_prefill
            and self.mesh is not None
            and int(self.mesh.shape.get("sequence", 1)) > 1
        )
        chunk = cfg.prefill_chunk
        if prefix is not None:
            # composition with sp_prefill: the LONG shared prefix was prefilled
            # sequence-parallel inside cache_prefix (its _start call dispatches
            # to the sp path); the short per-request suffix goes through the
            # offset chunked path here — SP where length lives, cache reuse
            # where repetition lives
            return self._start_with_prefix(
                prefix, tokens, lengths, batch, n, bucket, extra_cache, seed, cstate
            )
        if sp:
            seq = int(self.mesh.shape["sequence"])
            aligned = chunk_aligned(bucket, seq)  # each sequence shard gets equal columns
            tokens = np.pad(tokens, ((0, 0), (0, aligned - tokens.shape[1])), constant_values=cfg.pad_id)
            bucket = aligned
        elif chunk:
            bucket = chunk_aligned(bucket, chunk)  # bucket shape is moot once chunked
            tokens = np.pad(tokens, ((0, 0), (0, bucket - tokens.shape[1])), constant_values=cfg.pad_id)
        cache_len = max(bucket, max(cfg.prompt_buckets, default=0)) + cfg.max_new_tokens + extra_cache
        cache = self._place_cache(
            init_cache(self.module.config, batch, cache_len, kv_dtype=cfg.kv_cache_dtype)
        )
        key = jax.random.PRNGKey(seed)
        key, prefill_key = jax.random.split(key)
        row_valid = jnp.arange(batch) < n
        if sp:
            if self._sp_prefill_fn is None:
                self._sp_prefill_fn = self._build_sp_prefill()
            tok0, cache, last = self._sp_prefill_fn(
                self.params, jnp.asarray(tokens), jnp.asarray(all_lengths), cache, prefill_key, row_valid, *cstate
            )
        elif chunk and bucket > chunk:
            last, cache = self._chunked_prefill_loop(
                tokens, jnp.asarray(all_lengths), cache, row_valid, chunk
            )
            tok0 = self._first_token(self.params, last, prefill_key, *cstate)
        else:
            tok0, cache, last = self._prefill(
                self.params, jnp.asarray(tokens), jnp.asarray(all_lengths), cache, prefill_key, row_valid, *cstate
            )
        return self._finish_prefill(n, tok0, last, cache, jnp.asarray(all_lengths), row_valid, key, cstate)

    def _chunked_prefill_loop(self, tokens, lengths_dev, cache, row_valid, chunk: int, start: int = 0):
        """Run right-padded ``tokens`` through the chunked prefill fn in
        ``chunk``-column slices whose absolute positions begin at ``start``,
        accumulating each row's last-real-token hidden state."""
        last = jnp.zeros((tokens.shape[0], self.module.config.dim), jnp.float32)
        for c in range(0, tokens.shape[1], chunk):
            chunk_last, has, cache = self._prefill_chunk(
                self.params,
                jnp.asarray(tokens[:, c : c + chunk]),
                jnp.int32(start + c),
                lengths_dev,
                cache,
                row_valid,
            )
            last = jnp.where(has[:, None], chunk_last, last)
        return last, cache

    def _grammar_ids(self, constraint: Optional[Any], n: int, batch: int) -> np.ndarray:
        """Normalize a ``constraint=`` argument (int, or one int per prompt) to
        per-row grammar ids; synthetic padding rows ride FREE (id 0)."""
        gids = np.zeros((batch,), np.int64)
        if constraint is not None:
            con = np.asarray(constraint)
            if con.ndim == 0:
                gids[:n] = int(con)
            elif con.shape[0] == n:
                gids[:n] = con
            else:
                raise ValueError(f"constraint has {con.shape[0]} entries for {n} prompts")
        return gids

    def _finish_prefill(self, n, tok0, last, cache, lengths_dev, row_valid, key, cstate=()):
        eos = self.config.eos_id
        done = (tok0 == eos) if eos is not None else jnp.zeros(tok0.shape, bool)
        # synthetic batch-padding rows start done: they emit pads, never advance
        # their cache, and stay out of routed-expert capacity
        done = done | ~row_valid
        carry = (cache, tok0, lengths_dev, done, key)
        if cstate:
            # advance each row's DFA past its (constrained) first token; the
            # state rides as the carry's tail through the decode scan
            carry = carry + (self._cs_trans[cstate[0], tok0],)
        return n, tok0, last, carry

    def _start_with_prefix(
        self,
        prefix: PrefixCache,
        tokens: np.ndarray,
        lengths: np.ndarray,
        batch: int,
        n: int,
        bucket: int,
        extra_cache: int,
        seed: int,
        cstate: tuple = (),
    ):
        """Prefill only the per-request suffix: the prefix's K/V rows are pasted
        into slots ``[0, p0)`` of every cache row and the suffix flows through the
        chunked-prefill path with a start offset of ``p0`` (its positions — hence
        RoPE phases and visibility — continue where the prefix left off). The
        shared system-prompt cost was paid once in :meth:`cache_prefix`."""
        cfg = self.config
        p0 = prefix.length
        chunk = cfg.prefill_chunk or bucket
        aligned = chunk_aligned(bucket, chunk)
        if aligned > tokens.shape[1]:
            tokens = np.pad(
                tokens, ((0, 0), (0, aligned - tokens.shape[1])), constant_values=cfg.pad_id
            )
        cache_len = (
            p0 + max(aligned, max(cfg.prompt_buckets, default=0)) + cfg.max_new_tokens + extra_cache
        )
        cache = self._place_cache(
            init_cache(self.module.config, batch, cache_len, kv_dtype=cfg.kv_cache_dtype)
        )
        cache = _paste_prefix_rows(cache, prefix.layers)
        key = jax.random.PRNGKey(seed)
        key, prefill_key = jax.random.split(key)
        row_valid = jnp.arange(batch) < n
        # total sequence length = prefix + suffix; synthetic rows pretend one
        # suffix token (they are masked out of the forward via row_valid anyway)
        all_lengths = np.full((batch,), p0 + 1, np.int32)
        all_lengths[:n] = p0 + lengths
        lengths_dev = jnp.asarray(all_lengths)
        last, cache = self._chunked_prefill_loop(
            tokens, lengths_dev, cache, row_valid, chunk, start=p0
        )
        tok0 = self._first_token(self.params, last, prefill_key, *cstate)
        return self._finish_prefill(n, tok0, last, cache, lengths_dev, row_valid, key, cstate)

    def __call__(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        seed: int = 0,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ) -> np.ndarray:
        """Generate ``max_new_tokens`` per prompt; returns ``[len(prompts), max_new]``
        int32 (``pad_id`` after each example's ``eos_id``). With ``prefix`` (from
        :meth:`cache_prefix`), prompts are suffixes after the shared prefix and
        only they are prefilled. With ``config.draft`` set, decoding runs
        speculatively (same output law, fewer target dispatches). ``constraint``
        (an int, or one int per prompt, indexing ``config.constraints``; 0 = the
        FREE grammar) masks each row's decoding by its grammar's token DFA."""
        if self.config.draft is not None:
            return self._speculative()(prompts, seed=seed, prefix=prefix, constraint=constraint)
        n, tok0, _, carry = self._start(prompts, seed, prefix=prefix, constraint=constraint)
        steps = self.config.max_new_tokens - 1
        first = np.asarray(tok0)[:, None]
        if steps <= 0:
            return first[:n]
        rest, _, _ = self._decode(self.params, *carry, steps=steps)
        return np.concatenate([first, np.asarray(rest)], axis=1)[:n]

    def beam_search(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        num_beams: int = 4,
        length_penalty: float = 0.0,
        constraint: Optional[Any] = None,
    ) -> np.ndarray:
        """Deterministic beam search: returns the highest-sum-log-prob continuation
        of ``max_new_tokens`` per prompt (``[n_prompts, max_new]`` int32).

        Beams are batch rows: each prompt is prefilled ``num_beams`` times and the
        whole search runs as ONE jitted ``lax.scan`` — each step scores all beams,
        takes the top ``num_beams`` of the ``num_beams * vocab`` candidates per
        prompt, and physically gathers the KV cache rows to the surviving parents
        (decode streams the weights anyway; the cache gather is a small fraction
        of the step's HBM traffic). A beam that emits ``eos_id`` is finished: it
        keeps competing with its score frozen, padding from there on. With
        ``length_penalty`` > 0 final scores are divided by
        ``((5 + len) / 6) ** length_penalty`` (GNMT convention).

        ``constraint`` (an int or one per prompt, indexing ``config.constraints``)
        runs the search inside the grammar: each beam carries its DFA state
        (gathered alongside cache rows on reorder), candidate scores are the
        log-probs of the CONSTRAINED policy (logits masked by the beam's
        allowed set, then renormalized — the same distribution sampling draws
        from), and EOS competes only at accepting states.
        """
        cfg = self.config
        if num_beams < 1:
            raise ValueError("num_beams must be >= 1")
        n = len(prompts)
        # pad whole GROUPS (not rows) so the batch is exactly groups * num_beams;
        # a multiple of the data axis keeps both the prefill batch (groups) and
        # the search batch (groups * num_beams) shardable
        groups = 1 << max(0, (n - 1).bit_length())
        if self.mesh is not None and "data" in self.mesh.axis_names:
            data = int(self.mesh.shape["data"])
            groups = int(math.ceil(groups / data) * data)
        # prefill each UNIQUE prompt once (synthetic padding groups get _start's
        # row_valid masking, keeping them out of routed-expert capacity), then
        # tile every cache row to its num_beams slots — beams share the prompt
        _, _, last, carry = self._start(prompts, 0, batch_override=groups, constraint=constraint)
        cache, lengths = carry[0], carry[2]
        tile = jnp.arange(groups * num_beams) // num_beams
        cache = jax.tree_util.tree_map(lambda c: c[tile], cache)
        last, lengths = last[tile], lengths[tile]
        done = tile >= n  # synthetic groups only
        cstate = ()
        if self._cs is not None:
            # the search seeds from the PREFILL distribution (not _start's
            # sampled tok0), so every beam starts at its grammar's START state
            gids = self._grammar_ids(constraint, n, groups)
            cstate = (jnp.asarray(self._cs.start_states(gids))[tile],)
        fn = self._beam_fns.get(num_beams)
        if fn is None:
            fn = self._build_beam_fn(num_beams)
            self._beam_fns[num_beams] = fn
        out, scores, _ = fn(self.params, cache, last, lengths, done, *cstate)
        out = np.asarray(out).reshape(groups, num_beams, -1)[:n]
        scores = np.asarray(scores).reshape(groups, num_beams)[:n]
        if cfg.eos_id is not None and length_penalty > 0.0:
            lens = np.where(out == cfg.eos_id, 1, 0).argmax(axis=2)
            lens = np.where((out == cfg.eos_id).any(axis=2), lens + 1, out.shape[2])
            scores = scores / (((5.0 + lens) / 6.0) ** length_penalty)
        best = scores.argmax(axis=1)
        return out[np.arange(n), best]

    def _build_beam_fn(self, num_beams: int):
        cfg = self.config
        eos = cfg.eos_id
        pad = jnp.int32(cfg.pad_id)
        cs = self._cs

        def beam_fn(p, cache, last, lengths, done, *cstate):
            p = self._dequant_params(p)
            batch = last.shape[0]
            groups = batch // num_beams
            compute_dtype = getattr(getattr(self.module, "config", None), "dtype", jnp.bfloat16)

            def logprobs(hidden, st=None):
                logits = self._head_fn(p, hidden)
                if st is not None:
                    # the CONSTRAINED policy's distribution: mask, then
                    # renormalize — the same law sampling draws from
                    logits = self._constrain(logits, (st,))
                return jax.nn.log_softmax(logits, axis=-1)

            st = cstate[0] if cs is not None else None
            # first expansion from the PREFILL distribution: all beams of a group
            # share the prompt, so its top tokens seed distinct beams. With
            # num_beams > vocab only vocab distinct seeds exist; the surplus beams
            # start at -inf and join the pool as the tree widens in later steps.
            lp0 = logprobs(last.astype(compute_dtype), st).reshape(groups, num_beams, -1)
            vocab = lp0.shape[-1]
            k0 = min(num_beams, vocab)
            seed_scores, seed_tokens = jax.lax.top_k(lp0[:, 0], k0)  # [G, k0]
            scores = jnp.pad(seed_scores, ((0, 0), (0, num_beams - k0)), constant_values=-jnp.inf)
            first_tokens = jnp.pad(seed_tokens, ((0, 0), (0, num_beams - k0)), constant_values=int(pad))
            tok = jnp.where(done, pad, first_tokens.reshape(batch))
            beam_done = done | ((tok == eos) if eos is not None else jnp.zeros_like(done))
            out = jnp.full((batch, cfg.max_new_tokens), pad, jnp.int32).at[:, 0].set(tok)
            if cs is not None:
                st = jnp.where(done, st, self._cs_trans[st, tok])

            def body(carry, col):
                cache, tok, lengths, scores, beam_done, out, *cst = carry
                # feed each beam's pending token (decode convention: positions =
                # filled length; lengths advance after the feed)
                hidden, cache = self._apply_fn(
                    p, tok[:, None], lengths[:, None], cache, (~beam_done)[:, None]
                )
                lengths = lengths + jnp.where(beam_done, 0, 1)
                lp = logprobs(hidden[:, 0], cst[0] if cs is not None else None)
                lp = lp.reshape(groups, num_beams, vocab)
                flat_done = beam_done.reshape(groups, num_beams)
                # finished beams contribute exactly one frozen-score candidate
                # (their pad continuation); active beams expand over the vocab
                cand = scores[:, :, None] + jnp.where(flat_done[:, :, None], -jnp.inf, lp)
                pad_cand = jnp.where(flat_done, scores, -jnp.inf)  # [G, K]
                all_cand = jnp.concatenate([cand.reshape(groups, -1), pad_cand], axis=1)
                top_scores, top_idx = jax.lax.top_k(all_cand, num_beams)  # [G, K]
                is_pad_cand = top_idx >= num_beams * vocab
                parent = jnp.where(is_pad_cand, top_idx - num_beams * vocab, top_idx // vocab)
                token = jnp.where(is_pad_cand, pad, top_idx % vocab)

                # reorder every per-beam tensor to the surviving parents
                flat_parent = (jnp.arange(groups)[:, None] * num_beams + parent).reshape(batch)
                cache = jax.tree_util.tree_map(lambda c: c[flat_parent], cache)
                out = out[flat_parent]
                lengths = lengths[flat_parent]
                prev_done = beam_done[flat_parent]
                tok = token.reshape(batch)
                beam_done = prev_done | ((tok == eos) if eos is not None else jnp.zeros_like(prev_done))
                out = jax.vmap(lambda row, t: row.at[col].set(t))(out, jnp.where(prev_done, pad, tok))
                if cs is not None:
                    # DFA states follow their parent beams, then advance on the
                    # freshly chosen token (pad candidates keep their state)
                    stp = cst[0][flat_parent]
                    cst = (jnp.where(prev_done, stp, self._cs_trans[stp, tok]),)
                return (cache, tok, lengths, top_scores, beam_done, out, *cst), None

            carry = (cache, tok, lengths, scores, beam_done, out) + ((st,) if cs is not None else ())
            steps = cfg.max_new_tokens - 1
            if steps > 0:
                carry, _ = jax.lax.scan(body, carry, jnp.arange(1, steps + 1))
            cache, tok, lengths, scores, beam_done, out = carry[:6]
            # the final cache rides along so the donated input can alias
            return out, scores.reshape(batch), cache

        return jax.jit(beam_fn, donate_argnums=(1,))

    def stream(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        seed: int = 0,
        chunk_size: int = 16,
        prefix: Optional[PrefixCache] = None,
        constraint: Optional[Any] = None,
    ):
        """Incremental generation: yields ``[len(prompts), <=chunk_size]`` arrays of
        newly decoded tokens as they materialize (the first yield is the single
        prompt-sampled token). The decode compiles once per ``chunk_size``; when
        every row has emitted ``eos_id`` the stream ends early. Total tokens across
        yields equal ``__call__``'s output for the same seed. ``prefix`` works as
        in :meth:`__call__`. With ``config.draft`` set, streaming is speculative
        and yields follow :meth:`SpeculativeGenerator.stream`'s RAGGED shape (a
        list of per-row 1-D arrays) since rows advance at round granularity."""
        cfg = self.config
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if cfg.draft is not None:
            yield from self._speculative().stream(
                prompts, seed=seed, chunk_size=chunk_size, prefix=prefix, constraint=constraint
            )
            return
        # the last chunk may overshoot max_new_tokens; give its cache writes room
        n_chunks = max(0, -(-(cfg.max_new_tokens - 1) // chunk_size))
        extra = n_chunks * chunk_size - (cfg.max_new_tokens - 1)
        n, tok0, _, carry = self._start(
            prompts, seed, extra_cache=extra, prefix=prefix, constraint=constraint
        )
        yield np.asarray(tok0)[:n, None]
        produced = 1
        while produced < cfg.max_new_tokens:
            if bool(np.asarray(carry[3]).all()):
                return  # every row finished with eos
            toks, _, carry = self._decode(self.params, *carry, steps=chunk_size)
            take = min(chunk_size, cfg.max_new_tokens - produced)
            yield np.asarray(toks)[:n, :take]
            produced += take
