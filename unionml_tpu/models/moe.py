"""Mixture-of-Experts layer + decoder with expert parallelism over an ``expert`` axis.

The reference has no MoE (SURVEY.md §2.3 lists EP as absent; the mesh keeps an
``expert`` axis open per the build plan). The TPU-native design is the
Switch/Mixtral dense-dispatch formulation rather than per-rank alltoall calls:

- the router's top-k choice becomes one-hot **dispatch/combine tensors**, and
  token->expert movement is two einsums — large, static-shape matmuls the MXU
  likes, with no data-dependent control flow under ``jit``;
- expert FFN weights are stacked on a leading ``[n_experts, ...]`` dim and sharded
  ``P("expert", ...)``; the dispatched activations are sharding-constrained to
  ``P("expert", ...)`` on their expert dim, so **XLA emits the all-to-all** from the
  sharding propagation — the compiler-emitted analog of NCCL alltoall in GPU MoE
  stacks;
- each expert processes a fixed ``capacity`` of tokens (static shapes); overflow
  tokens are dropped by the dispatch mask and pass through the residual, the
  standard TPU-friendly trade (capacity_factor controls the drop rate).

Load balancing uses the Switch aux loss (fraction-of-tokens x mean-router-prob per
expert, scaled by n_experts); the layer ``sow``s it under the ``"losses"``
collection and :func:`moe_lm_loss` adds it to the LM loss.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from unionml_tpu.models.layers import MLP, Attention, IotaEmbed, RMSNorm
from unionml_tpu.parallel.sharding import PartitionRules

Dtype = Any


def top_k_dispatch(
    router_probs: jax.Array, k: int, capacity: int, valid: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build dispatch/combine tensors from router probabilities.

    :param router_probs: ``[n_tokens, n_experts]`` softmax outputs.
    :param valid: optional ``[n_tokens]`` bool — False tokens (padding) claim no
        expert capacity, get zero dispatch/combine rows, and are excluded from the
        aux loss. Without it, identical pad embeddings all route to the same
        experts and can crowd real tokens out of capacity.
    :returns: ``(dispatch [N, E, C] bool-ish, combine [N, E, C], aux_loss scalar)``.
    """
    n_tokens, n_experts = router_probs.shape
    gate_vals, gate_idx = jax.lax.top_k(router_probs, k)  # [N, k]
    # Mixtral-style renormalization: the k selected gates sum to 1 per token
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((n_tokens, n_experts, capacity), router_probs.dtype)
    combine = jnp.zeros((n_tokens, n_experts, capacity), router_probs.dtype)
    counts = jnp.zeros((n_experts,), jnp.int32)
    for slot in range(k):  # k is small and static; unrolled at trace time
        onehot = jax.nn.one_hot(gate_idx[:, slot], n_experts, dtype=jnp.int32)  # [N, E]
        if valid is not None:
            onehot = onehot * valid.astype(jnp.int32)[:, None]
        # position of each token within its chosen expert's capacity buffer
        pos = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :]
        counts = counts + onehot.sum(axis=0)
        within = (pos < capacity) & (onehot > 0)
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=router_probs.dtype)  # [N, E, C]
        slot_dispatch = pos_oh * within.astype(router_probs.dtype)[..., None]
        dispatch = dispatch + slot_dispatch
        combine = combine + gate_vals[:, slot, None, None] * slot_dispatch

    # Switch load-balance loss: n_experts * sum_e f_e * p_e, minimized at uniform
    top1 = jax.nn.one_hot(gate_idx[:, 0], n_experts)
    if valid is None:
        token_frac = top1.mean(axis=0)
        prob_frac = router_probs.mean(axis=0)
    else:
        w = valid.astype(router_probs.dtype)[:, None]
        denom = jnp.maximum(w.sum(), 1.0)
        token_frac = (top1 * w).sum(axis=0) / denom
        prob_frac = (router_probs * w).sum(axis=0) / denom
    aux_loss = n_experts * jnp.sum(token_frac * prob_frac)
    return dispatch, combine, aux_loss


class MoELayer(nn.Module):
    """Top-k routed expert FFNs replacing a dense MLP.

    Expert weights live under ``experts/...`` with a leading ``[n_experts]`` dim
    (``nn.vmap``); shard them ``P("expert", ...)`` via :func:`moe_partition_rules`.
    """

    n_experts: int
    hidden_dim: int
    k: int = 2
    capacity_factor: float = 1.25
    gated: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, token_mask: Optional[jax.Array] = None) -> jax.Array:
        batch, length, dim = x.shape
        n_tokens = batch * length
        tokens = x.reshape(n_tokens, dim)
        capacity = max(1, int(self.capacity_factor * self.k * n_tokens / self.n_experts))

        # router runs in f32: routing decisions are precision-sensitive
        router_logits = nn.Dense(
            self.n_experts, use_bias=False, dtype=jnp.float32, param_dtype=self.param_dtype, name="router"
        )(tokens.astype(jnp.float32))
        valid = token_mask.reshape(n_tokens) if token_mask is not None else None
        dispatch, combine, aux_loss = top_k_dispatch(
            jax.nn.softmax(router_logits, -1), self.k, capacity, valid
        )
        self.sow("losses", "moe_aux_loss", aux_loss)

        # dispatch: one einsum, [E, C, D] sharded over the expert axis -> XLA
        # inserts the all-to-all between the data-sharded and expert-sharded layouts
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype), tokens.astype(self.dtype))
        expert_in = _constrain(expert_in, P("expert", None, None))

        experts = nn.vmap(
            MLP,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(
            hidden_dim=self.hidden_dim,
            gated=self.gated,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="experts",
        )
        expert_out = experts(expert_in)  # [E, C, D]
        expert_out = _constrain(expert_out, P("expert", None, None))

        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), expert_out)
        return out.reshape(batch, length, dim)


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Apply a sharding constraint when running under a mesh that has the axes."""
    names = set()
    for entry in spec:
        if entry is not None:
            names.update(entry if isinstance(entry, tuple) else (entry,))
    # mesh discovery may drift across jax versions — degrade to "no mesh visible";
    # but once a mesh with the right axes is found, constraint errors must surface
    # (a swallowed error here silently turns expert parallelism into replication)
    abstract = None
    try:
        abstract = jax.sharding.get_abstract_mesh()  # set by jax.sharding.use_mesh
    except AttributeError:
        pass
    if abstract is not None and not abstract.empty:
        if not names.issubset(abstract.axis_names):
            return x
        return jax.lax.with_sharding_constraint(x, spec)
    try:
        # `with mesh:` (Mesh context manager) sets only the physical mesh
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
    except ImportError:
        return x
    if mesh.empty or not names.issubset(mesh.axis_names):
        return x
    return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 8
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    n_experts: int = 8
    k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2  # every Nth block uses MoE FFN (1 = all blocks, Mixtral-style)
    max_seq_len: int = 4096
    rope_theta: float = 500000.0
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def tiny(cls, **overrides: Any) -> "MoEConfig":
        defaults = dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
            n_experts=4, k=2, moe_every=1, max_seq_len=128,
        )
        defaults.update(overrides)
        return cls(**defaults)


class MoEBlock(nn.Module):
    """Pre-norm decoder block with a routed-experts FFN."""

    config: MoEConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
        cache: Optional[Any] = None,
        token_mask: Optional[jax.Array] = None,
    ) -> Any:
        cfg = self.config
        attn_out = Attention(
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            causal=True,
            rope=True,
            rope_theta=cfg.rope_theta,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="attn",
        )(RMSNorm(dtype=cfg.dtype, name="attn_norm")(x), positions, mask, cache)
        if cache is not None:
            attn_out, cache = attn_out
        x = x + attn_out
        x = x + MoELayer(
            n_experts=cfg.n_experts,
            hidden_dim=cfg.hidden_dim,
            k=cfg.k,
            capacity_factor=cfg.capacity_factor,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="moe",
        )(RMSNorm(dtype=cfg.dtype, name="moe_norm")(x), token_mask)
        return (x, cache) if cache is not None else x


class MoETransformer(nn.Module):
    """Causal LM with routed-expert FFNs (Mixtral-family shape): tokens -> logits.

    Follows the same cache contract as :class:`~unionml_tpu.models.llama.Llama`, so
    :class:`~unionml_tpu.models.generate.Generator` serves it unchanged.
    ``token_mask`` (``[B, L]`` bool, False = padding) keeps pad tokens from
    claiming expert capacity — without it, bucketed/batch-padded serving would
    let identical pad embeddings crowd real tokens out of their experts.
    Capacity under incremental decoding is per routed group (per decode step);
    size ``capacity_factor`` for the serving batch, not the training sequence.
    """

    config: MoEConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        return_hidden: bool = False,
        cache: Optional[Tuple[Any, ...]] = None,
        token_mask: Optional[jax.Array] = None,
    ) -> Any:
        from unionml_tpu.models.layers import TransformerBlock

        cfg = self.config
        x = IotaEmbed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed")(tokens)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        new_cache = []
        for i in range(cfg.n_layers):
            moe_block = i % cfg.moe_every == cfg.moe_every - 1
            if moe_block:
                block = MoEBlock(cfg, name=f"layer_{i}")
            else:
                block = TransformerBlock(
                    n_heads=cfg.n_heads,
                    n_kv_heads=cfg.n_kv_heads,
                    hidden_dim=cfg.hidden_dim,
                    decoder=True,
                    rope=True,
                    rope_theta=cfg.rope_theta,
                    dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    name=f"layer_{i}",
                )
            extra = (token_mask,) if moe_block else ()  # only routed blocks consume it
            if cache is not None:
                x, layer_cache = block(x, positions, None, cache[i], *extra)
                new_cache.append(layer_cache)
            else:
                x = block(x, positions, None, None, *extra)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        if return_hidden:
            return (x, tuple(new_cache)) if cache is not None else x
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="lm_head"
        )(x)
        return (logits, tuple(new_cache)) if cache is not None else logits


def moe_partition_rules() -> PartitionRules:
    """Expert-parallel layout: stacked expert weights shard their leading dim over
    ``expert``; within an expert the megatron TP pattern applies on the trailing
    dims; everything else follows the llama rules."""
    return PartitionRules(
        [
            (r"experts/(wi|wg)/kernel", P("expert", "fsdp", "model")),
            (r"experts/wo/kernel", P("expert", "model", "fsdp")),
            (r"experts/.*(bias|scale)", P("expert")),
            (r"router/kernel", P()),
            (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
            (r"attn/o_proj/kernel", P("model", "fsdp")),
            # dense interleaved blocks (moe_every > 1) follow the llama MLP layout
            (r"mlp/(wi|wg)/kernel", P("fsdp", "model")),
            (r"mlp/wo/kernel", P("model", "fsdp")),
            (r"embed/embedding", P("model", "fsdp")),
            (r"lm_head/kernel", P("fsdp", "model")),
            (r".*(norm|scale|bias)", P()),
        ]
    )


def moe_lm_loss(module: MoETransformer, params: Any, batch: Any) -> jax.Array:
    """Next-token cross-entropy + weighted router load-balance aux loss.

    ``batch``: tokens array or ``(tokens, loss_mask)`` — same contract as
    :func:`unionml_tpu.models.llama.causal_lm_loss`.
    """
    import optax

    tokens, mask = (batch if isinstance(batch, (tuple, list)) and len(batch) == 2 else (batch, None))
    if isinstance(tokens, (tuple, list)):
        tokens = tokens[0]
    logits, state = module.apply({"params": params}, tokens, mutable=["losses"])
    targets = tokens[:, 1:]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1].astype(jnp.float32), targets)
    aux_terms = jax.tree_util.tree_leaves(state.get("losses", {}))
    aux = sum(jnp.sum(t) for t in aux_terms) if aux_terms else jnp.float32(0.0)
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        ce = (losses * m).sum() / jnp.maximum(m.sum(), 1.0)
    else:
        ce = losses.mean()
    return ce + module.config.aux_loss_weight * aux
