"""Model library: flagship flax models for the benchmark configs (BASELINE.json)."""

from unionml_tpu.models.bert import BertConfig, BertEncoder, bert_partition_rules, classification_loss  # noqa: F401
from unionml_tpu.models.generate import (  # noqa: F401
    DraftSpec,
    GenerationConfig,
    Generator,
    PrefixCache,
    init_cache,
    sample_tokens,
)
from unionml_tpu.models.speculative import SpeculativeGenerator  # noqa: F401
from unionml_tpu.models.structured import (  # noqa: F401
    ConstraintSet,
    TokenConstraint,
    compile_regex,
    json_object,
    literal_choice,
    stop_sequences,
    vocab_from_tokenizer,
)
from unionml_tpu.models.llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    causal_lm_loss,
    chunked_causal_lm_loss,
    llama_partition_rules,
    lora_optimizer,
    lora_param_labels,
)
from unionml_tpu.models.mlp import MLPClassifier, MLPConfig  # noqa: F401
from unionml_tpu.models.moe import (  # noqa: F401
    MoEConfig,
    MoELayer,
    MoETransformer,
    moe_lm_loss,
    moe_partition_rules,
    top_k_dispatch,
)
from unionml_tpu.models.vit import (  # noqa: F401
    PipelinedViT,
    ViT,
    ViTConfig,
    pipelined_vit_partition_rules,
    vit_partition_rules,
)
