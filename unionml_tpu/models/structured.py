"""Grammar-constrained (structured) decoding: regex -> token-level DFA tables.

The reference has no generation stack at all (SURVEY.md §2.3 — no attention or
inference code anywhere in unionml/), so structured output is pure new surface;
it is table stakes for a production serving engine (JSON mode, enum outputs,
tool-call argument shapes). The TPU-native design constraint is that the decode
loop is ONE compiled ``lax.scan`` — so the grammar must be data, not control
flow:

- a regex is compiled on the host to a char-level DFA (Thompson NFA + subset
  construction), then projected onto the token vocabulary: ``trans[s, t]`` is
  the DFA state after emitting token ``t`` from state ``s`` and
  ``allowed[s, t]`` whether that emission keeps the output inside the language;
- the tables ride to the device once; inside the jitted decode step the
  constraint is two gathers and a ``where`` — ``logits`` masked by
  ``allowed[state]``, ``state`` advanced by ``trans[state, token]``. No
  data-dependent Python control flow, no recompilation per grammar.

:class:`ConstraintSet` unions several grammars into ONE table pair by
renumbering states; a row's grammar is then nothing but its start state, so a
single compiled decode program serves every grammar — per-request constraints
in a continuously-batched server cost zero extra compiles.

Token-level liveness: a char-level-live DFA state can still be a dead end for a
given vocabulary (no token realizes any escaping path). Tables are pruned to
token-level-live states by a backwards fixed point, so every reachable state
always has at least one allowed token (EOS counts at accepting states) — the
masked logits row can never be all ``-inf``.

Budget truncation caveat (shared by every structured-output engine): if
``max_new_tokens`` runs out before the DFA reaches an accepting state, the
emitted prefix matches a prefix of the language, not necessarily a full
sentence of it.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

__all__ = [
    "TokenConstraint",
    "ConstraintSet",
    "compile_regex",
    "literal_choice",
    "json_object",
    "stop_sequences",
    "vocab_from_tokenizer",
]


# ---------------------------------------------------------------------------
# Regex AST. The supported subset: literals, escapes (\d \w \s and inverses,
# \n \t \r, escaped metachars), classes [a-z0-9_] with ranges and negation,
# '.', quantifiers * + ? {m} {m,} {m,n}, alternation |, grouping (). This is
# the regular (finite-automaton) core — no backrefs/lookarounds, which have no
# DFA and therefore no place in a fixed-shape decode step.


@dataclasses.dataclass(frozen=True)
class _CharSet:
    chars: FrozenSet[str]
    negated: bool = False

    def resolve(self, alphabet: FrozenSet[str]) -> FrozenSet[str]:
        return frozenset(alphabet - self.chars) if self.negated else self.chars


@dataclasses.dataclass(frozen=True)
class _Node:
    kind: str  # "chars" | "concat" | "alt" | "repeat"
    chars: Optional[_CharSet] = None
    children: Tuple["_Node", ...] = ()
    lo: int = 0
    hi: Optional[int] = None  # None = unbounded


_DIGITS = frozenset("0123456789")
_WORD = frozenset("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")
_ESCAPES = {
    "d": _CharSet(_DIGITS),
    "D": _CharSet(_DIGITS, negated=True),
    "w": _CharSet(_WORD),
    "W": _CharSet(_WORD, negated=True),
    "s": _CharSet(_SPACE),
    "S": _CharSet(_SPACE, negated=True),
    "n": _CharSet(frozenset("\n")),
    "t": _CharSet(frozenset("\t")),
    "r": _CharSet(frozenset("\r")),
}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str) -> ValueError:
        return ValueError(f"regex error at position {self.i} in {self.p!r}: {msg}")

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> _Node:
        node = self.alt(depth=0)
        if self.i != len(self.p):
            raise self.error(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self, depth: int = 1) -> _Node:
        branches = [self.concat(depth)]
        while self.peek() == "|":
            self.next()
            branches.append(self.concat(depth))
        if len(branches) == 1:
            return branches[0]
        return _Node("alt", children=tuple(branches))

    def concat(self, depth: int = 1) -> _Node:
        parts: List[_Node] = []
        while self.peek() not in (None, "|", ")"):
            # Anchors are redundant under the promised fullmatch semantics —
            # but ONLY at top-level branch edges, where a branch edge IS a
            # string edge. There `^`/`$` are no-ops (the common `^...$`
            # spelling just works). Everywhere else — mid-branch, or anywhere
            # inside a group, where a branch edge is a mid-string position
            # (e.g. `(a$)b`, `a(^b)`) — re.fullmatch semantics differ from
            # both "literal" and "no-op", so an explicit error beats silently
            # compiling a different language.
            if self.peek() == "^":
                if parts or depth > 0:
                    raise self.error(
                        "'^' anchor is only supported at the pattern start "
                        "(fullmatch makes it redundant there; use \\^ for a literal '^')"
                    )
                self.next()
                continue
            if self.peek() == "$":
                if depth > 0:
                    raise self.error(
                        "'$' anchor is only supported at the pattern end "
                        "(fullmatch makes it redundant there; use \\$ for a literal '$')"
                    )
                self.next()
                if self.peek() not in (None, "|", "$"):
                    raise self.error(
                        "'$' anchor mid-pattern never matches under fullmatch "
                        "semantics (use \\$ for a literal '$')"
                    )
                continue
            parts.append(self.repeat())
        return _Node("concat", children=tuple(parts))

    def repeat(self) -> _Node:
        node = self.atom()
        while self.peek() in ("*", "+", "?", "{"):
            ch = self.peek()
            if ch == "{":
                save = self.i
                bounds = self._brace_bounds()
                if bounds is None:
                    self.i = save
                    break  # a literal '{' with no valid quantifier body
                lo, hi = bounds
            else:
                self.next()
                lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[ch]
            node = _Node("repeat", children=(node,), lo=lo, hi=hi)
        return node

    def _brace_bounds(self) -> Optional[Tuple[int, Optional[int]]]:
        """Parse ``{m}``/``{m,}``/``{m,n}``/``{,n}`` after a consumed ``{``;
        ``None`` = not a quantifier (the brace is a literal, matching how
        ``re`` treats e.g. ``a{-2}`` or ``a{ 2}``)."""
        self.next()  # consume '{'
        body = ""
        while self.peek() not in (None, "}"):
            body += self.next()
        if self.peek() != "}":
            return None
        self.next()
        # strictly (possibly empty) digits around at most one comma — int()
        # would also accept "-2" / " 2", silently compiling a different
        # language than re does. Python 3.12 semantics: {m}, {m,}, {,n}, and
        # bare {,} (= {0,}) are quantifiers; anything else is a literal brace.
        head, sep, tail = body.partition(",")
        if (head and not head.isdigit()) or (tail and not tail.isdigit()):
            return None
        if not sep:
            if not head:
                return None  # "{}" is a literal
            lo = int(head)
            return lo, lo
        lo = int(head) if head else 0
        hi = int(tail) if tail else None
        if hi is not None and hi < lo:
            raise self.error(f"bad quantifier bounds {{{body}}}")
        return lo, hi

    def atom(self) -> _Node:
        ch = self.peek()
        if ch is None:
            raise self.error("unexpected end of pattern")
        if ch == "(":
            self.next()
            node = self.alt()
            if self.peek() != ")":
                raise self.error("unbalanced parenthesis")
            self.next()
            return node
        if ch == "[":
            return _Node("chars", chars=self._char_class())
        if ch == ".":
            self.next()
            return _Node("chars", chars=_CharSet(frozenset("\n"), negated=True))
        if ch == "\\":
            self.next()
            esc = self.next() if self.peek() is not None else None
            if esc is None:
                raise self.error("dangling backslash")
            return _Node("chars", chars=_ESCAPES.get(esc, _CharSet(frozenset(esc))))
        if ch in ")|*+?":
            raise self.error(f"unexpected {ch!r}")
        self.next()
        return _Node("chars", chars=_CharSet(frozenset(ch)))

    def _char_class(self) -> _CharSet:
        self.next()  # consume '['
        negated = self.peek() == "^"
        if negated:
            self.next()
        chars: Set[str] = set()
        negated_parts: List[_CharSet] = []
        first = True
        while self.peek() != "]" or first:
            first = False
            ch = self.peek()
            if ch is None:
                raise self.error("unterminated character class")
            if ch == "\\":
                self.next()
                if self.peek() is None:
                    raise self.error("dangling backslash in character class")
                esc = self.next()
                part = _ESCAPES.get(esc, _CharSet(frozenset(esc)))
                if part.negated:
                    negated_parts.append(part)
                else:
                    chars |= part.chars
                continue
            self.next()
            if self.peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                self.next()  # consume '-'
                end = self.next()
                if ord(end) < ord(ch):
                    raise self.error(f"bad range {ch}-{end}")
                chars |= {chr(c) for c in range(ord(ch), ord(end) + 1)}
            else:
                chars.add(ch)
        self.next()  # consume ']'
        if negated_parts:
            # [\D...] style classes inside a positive class need the alphabet to
            # resolve; rare enough to refuse rather than approximate
            raise self.error("negated escape inside a character class is unsupported")
        return _CharSet(frozenset(chars), negated=negated)


def _ast_chars(node: _Node) -> Set[str]:
    if node.kind == "chars":
        return set(node.chars.chars)
    out: Set[str] = set()
    for child in node.children:
        out |= _ast_chars(child)
    return out


# ---------------------------------------------------------------------------
# Thompson NFA -> subset-construction DFA over an explicit (projected) alphabet.


class _NFA:
    def __init__(self) -> None:
        self.eps: List[Set[int]] = []
        self.edges: List[List[Tuple[FrozenSet[str], int]]] = []

    def state(self) -> int:
        self.eps.append(set())
        self.edges.append([])
        return len(self.eps) - 1


def _build_nfa(node: _Node, nfa: _NFA, alphabet: FrozenSet[str]) -> Tuple[int, int]:
    """Returns (entry, exit) state ids for ``node``'s fragment."""
    if node.kind == "chars":
        s, e = nfa.state(), nfa.state()
        nfa.edges[s].append((node.chars.resolve(alphabet), e))
        return s, e
    if node.kind == "concat":
        s = e = nfa.state()
        for child in node.children:
            cs, ce = _build_nfa(child, nfa, alphabet)
            nfa.eps[e].add(cs)
            e = ce
        return s, e
    if node.kind == "alt":
        s, e = nfa.state(), nfa.state()
        for child in node.children:
            cs, ce = _build_nfa(child, nfa, alphabet)
            nfa.eps[s].add(cs)
            nfa.eps[ce].add(e)
        return s, e
    if node.kind == "repeat":
        (child,) = node.children
        s = e = nfa.state()
        for _ in range(node.lo):  # mandatory copies
            cs, ce = _build_nfa(child, nfa, alphabet)
            nfa.eps[e].add(cs)
            e = ce
        if node.hi is None:  # Kleene tail
            cs, ce = _build_nfa(child, nfa, alphabet)
            nfa.eps[e].add(cs)
            nfa.eps[ce].add(cs)
            out = nfa.state()
            nfa.eps[e].add(out)
            nfa.eps[ce].add(out)
            return s, out
        tail_exits = [e]
        for _ in range(node.hi - node.lo):  # optional copies
            cs, ce = _build_nfa(child, nfa, alphabet)
            nfa.eps[e].add(cs)
            e = ce
            tail_exits.append(e)
        out = nfa.state()
        for t in tail_exits:
            nfa.eps[t].add(out)
        return s, out
    raise AssertionError(node.kind)


def _eps_closure(nfa: _NFA, states: FrozenSet[int]) -> FrozenSet[int]:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _char_dfa(
    pattern: str, alphabet: FrozenSet[str]
) -> Tuple[List[Dict[str, int]], List[bool]]:
    """Subset-construction DFA: returns (transitions, accepting) with state 0 the
    start state; missing dict entries are dead."""
    ast = _Parser(pattern).parse()
    alphabet = frozenset(alphabet | _ast_chars(ast))
    nfa = _NFA()
    entry, exit_ = _build_nfa(ast, nfa, alphabet)
    start = _eps_closure(nfa, frozenset([entry]))
    index: Dict[FrozenSet[int], int] = {start: 0}
    trans: List[Dict[str, int]] = [{}]
    accepting: List[bool] = [exit_ in start]
    work = [start]
    while work:
        stateset = work.pop()
        si = index[stateset]
        by_char: Dict[str, Set[int]] = {}
        for s in stateset:
            for charset, target in nfa.edges[s]:
                for ch in charset:
                    by_char.setdefault(ch, set()).add(target)
        for ch, targets in by_char.items():
            nxt = _eps_closure(nfa, frozenset(targets))
            if nxt not in index:
                index[nxt] = len(trans)
                trans.append({})
                accepting.append(exit_ in nxt)
                work.append(nxt)
            trans[si][ch] = index[nxt]
    # char-level liveness: drop states that cannot reach an accepting state
    n = len(trans)
    live = [accepting[i] for i in range(n)]
    changed = True
    while changed:
        changed = False
        for i in range(n):
            if not live[i] and any(live[t] for t in trans[i].values()):
                live[i] = True
                changed = True
    if not live[0]:
        raise ValueError(f"regex {pattern!r} matches no string")
    for i in range(n):
        trans[i] = {ch: t for ch, t in trans[i].items() if live[t]}
    return trans, accepting


# ---------------------------------------------------------------------------
# Token projection.


@dataclasses.dataclass(frozen=True)
class TokenConstraint:
    """One grammar projected onto a token vocabulary.

    ``trans[s, t]``: state after emitting token id ``t`` from state ``s``
    (meaningful only where ``allowed[s, t]``). ``allowed[s, t]``: whether token
    ``t`` keeps the output inside the language from state ``s`` (for the EOS
    column: whether the output so far is a complete sentence of it). State 0 is
    the start state. Build with :func:`compile_regex` / :func:`literal_choice`.
    """

    trans: np.ndarray  # [S, V] int32
    allowed: np.ndarray  # [S, V] bool
    eos_id: int

    @property
    def n_states(self) -> int:
        return int(self.trans.shape[0])

    @property
    def vocab_size(self) -> int:
        return int(self.trans.shape[1])


def compile_regex(pattern: str, vocab: Sequence[str], eos_id: int) -> TokenConstraint:
    """Compile ``pattern`` (fullmatch semantics, like ``re.fullmatch``) into a
    :class:`TokenConstraint` over ``vocab`` — ``vocab[t]`` is the decoded text
    of token id ``t``. Empty-string tokens (pads, non-text specials) are never
    allowed; ``eos_id`` is allowed exactly at accepting states. Raises if the
    language is empty or no vocabulary tokenization can realize it."""
    if not 0 <= eos_id < len(vocab):
        raise ValueError(f"eos_id {eos_id} outside vocab of {len(vocab)}")
    alphabet = frozenset(ch for tok in vocab for ch in tok)
    ctrans, caccept = _char_dfa(pattern, alphabet)
    n_char_states = len(ctrans)

    # vectorized projection: fold each token's chars over ALL states at once
    # (numpy gathers, -1 = dead) — O(V * len * S) array steps instead of a
    # pure-Python walk per (state, token) pair, which matters at real-tokenizer
    # vocab sizes (32k-128k) at server startup
    chars = sorted({ch for row in ctrans for ch in row})
    char_ix = {ch: i for i, ch in enumerate(chars)}
    cmat = np.full((n_char_states, len(chars) + 1), -1, np.int64)  # last col = unknown char
    for s, row in enumerate(ctrans):
        for ch, t in row.items():
            cmat[s, char_ix[ch]] = t

    V = len(vocab)
    trans = np.zeros((n_char_states, V), np.int32)
    allowed = np.zeros((n_char_states, V), bool)
    all_states = np.arange(n_char_states)
    for t, text in enumerate(vocab):
        if t == eos_id or text == "":
            continue
        cur = all_states
        for ch in text:
            ci = char_ix.get(ch, len(chars))
            cur = np.where(cur >= 0, cmat[np.maximum(cur, 0), ci], -1)
            if not (cur >= 0).any():
                break
        ok = cur >= 0
        trans[ok, t] = cur[ok]
        allowed[:, t] = ok
    # token-level liveness: a char-live state can still be a dead end for THIS
    # vocab (no token realizes an escaping path). Backwards fixed point; then
    # transitions into token-dead states are disallowed, so every reachable
    # state keeps >= 1 allowed token and the masked logits row is never all -inf.
    live = np.asarray(caccept, bool).copy()
    while True:
        reach_live = (allowed & live[trans]).any(axis=1)
        new_live = live | reach_live
        if (new_live == live).all():
            break
        live = new_live
    if not live[0]:
        raise ValueError(
            f"regex {pattern!r} is unreachable with this vocabulary "
            "(no token sequence spells a sentence of it)"
        )
    allowed &= live[trans]
    for s in np.flatnonzero(np.asarray(caccept, bool)):
        trans[s, eos_id] = s  # terminal self-loop; the row is done after EOS
        allowed[s, eos_id] = True
    keep = np.flatnonzero(live)
    remap = np.full(n_char_states, -1, np.int64)
    remap[keep] = np.arange(len(keep))
    trans = remap[trans[keep]].astype(np.int32)
    trans[trans < 0] = 0  # disallowed entries; value never read
    return TokenConstraint(trans=trans, allowed=allowed[keep], eos_id=eos_id)


def literal_choice(choices: Sequence[str], vocab: Sequence[str], eos_id: int) -> TokenConstraint:
    """Constrain output to exactly one of ``choices`` (an enum — classifier
    labels, tool names). Sugar over :func:`compile_regex` with escaping."""
    if not choices:
        raise ValueError("choices must be non-empty")
    return compile_regex("|".join(_escape(s) for s in choices), vocab, eos_id)


_ESCAPE_META = "\\.[](){}|*+?^$-"


def _escape(text: str) -> str:
    return "".join("\\" + c if c in _ESCAPE_META else c for c in text)


#: regex fragments for flat JSON values (no nesting — nested JSON is not
#: regular; bound the shape instead of the grammar)
JSON_VALUE_PATTERNS = {
    # control chars excluded: JSON forbids raw \n/\t/\r inside strings, and a
    # grammar that allows them forces output json.loads rejects
    "string": r'"[^"\\\n\t\r]*"',
    "number": r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?",
    "integer": r"-?(0|[1-9][0-9]*)",
    "boolean": r"(true|false)",
    "null": r"null",
}


def json_object(
    fields: Dict[str, str], vocab: Sequence[str], eos_id: int, *, whitespace: bool = True
) -> TokenConstraint:
    """A grammar for a FLAT JSON object with exactly these keys, in order.

    ``fields`` maps key -> value pattern: a name from
    :data:`JSON_VALUE_PATTERNS` (``"string"``, ``"number"``, ``"integer"``,
    ``"boolean"``, ``"null"``) or a raw regex for the value (e.g. an enum
    ``'("red"|"green")'``). Keys are emitted in dict order — fixed key order is
    what makes the object a REGULAR language (arbitrary key order is factorial
    in alternations; nesting is not regular at all — for those, generate into a
    string field and parse downstream).

    >>> g = json_object({"name": "string", "age": "integer"}, vocab, eos_id)
    >>> # accepts {"name": "ada", "age": 36} modulo whitespace

    ``whitespace=True`` permits up to 4 blanks/newlines where JSON allows them
    — BOUNDED on purpose: an unbounded ``[ \\t\\n]*`` lets a
    whitespace-leaning model burn the whole token budget on blanks without
    ever reaching the accept state (observed with an untrained model).
    """
    if not fields:
        raise ValueError("fields must be non-empty")
    ws = r"[ \t\n]{0,4}" if whitespace else ""
    parts = []
    for key, value in fields.items():
        if any(c in key for c in '"\\') or any(ord(c) < 0x20 for c in key):
            # such keys would need JSON string escaping inside the emitted
            # text; refusing beats silently forcing invalid JSON
            raise ValueError(f"key {key!r} contains characters needing JSON escaping")
        if value not in JSON_VALUE_PATTERNS and value.isidentifier():
            # identifier-shaped non-names are almost certainly typos ('bool'
            # for 'boolean'); a raw-regex value always contains metachars/quotes
            raise ValueError(
                f"unknown value type {value!r}; expected one of {sorted(JSON_VALUE_PATTERNS)} "
                "or a raw regex"
            )
        value_pat = JSON_VALUE_PATTERNS.get(value, value)
        # plain (...) groups: this dialect has no captures, so grouping is free
        parts.append(f'"{_escape(key)}"{ws}:{ws}({value_pat})')
    body = (f"{ws},{ws}").join(parts)
    return compile_regex(f"\\{{{ws}{body}{ws}\\}}", vocab, eos_id)


def stop_sequences(stops: Sequence[str], vocab: Sequence[str], eos_id: int) -> TokenConstraint:
    """A constraint enforcing STOP STRINGS: generation is free until any of
    ``stops`` completes in the emitted text, after which only EOS is allowed —
    the stream ends with the stop string, one token later (the OpenAI-style
    ``stop=`` knob, expressed as a grammar so every engine and composition —
    batcher, speculative, beam, paged, preemption-resume — inherits it with
    zero new machinery).

    Built directly as an Aho-Corasick automaton over the stop strings (the
    "text not containing X" language needs complement/lookahead the regex
    dialect deliberately lacks). Token rule: a token whose text completes a
    stop AT ITS END transitions to the must-EOS state; a token that would run
    PAST a completion mid-text is disallowed (the model takes a shorter
    tokenization of the same text — single-char tokens keep this live); EOS is
    allowed everywhere (free generation may end at will)."""
    if not stops or any(not s for s in stops):
        raise ValueError("stops must be non-empty strings")
    if not 0 <= eos_id < len(vocab):
        raise ValueError(f"eos_id {eos_id} outside vocab of {len(vocab)}")
    # Aho-Corasick: trie states over stop prefixes + failure links -> a total
    # transition function (a DFA) with match flags
    trie: List[Dict[str, int]] = [{}]
    match: List[bool] = [False]
    for stop in stops:
        s = 0
        for ch in stop:
            if ch not in trie[s]:
                trie.append({})
                match.append(False)
                trie[s][ch] = len(trie) - 1
            s = trie[s][ch]
        match[s] = True
    fail = [0] * len(trie)
    dq = collections.deque(trie[0].values())
    while dq:
        s = dq.popleft()
        for ch, t in trie[s].items():
            dq.append(t)
            f = fail[s]
            while f and ch not in trie[f]:
                f = fail[f]
            fail[t] = trie[f][ch] if ch in trie[f] and trie[f][ch] != t else 0
            match[t] = match[t] or match[fail[t]]

    def step(s: int, ch: str) -> int:
        while s and ch not in trie[s]:
            s = fail[s]
        return trie[s].get(ch, 0)

    # totalize into a dense char table so the token projection is the same
    # vectorized numpy fold compile_regex uses — a pure-Python per-(state,
    # token, char) walk is seconds of host startup at real vocab sizes
    chars = sorted({ch for s in stops for ch in s})
    char_ix = {ch: i for i, ch in enumerate(chars)}
    S = len(trie)
    cmat = np.zeros((S, len(chars) + 1), np.int64)  # last col: any other char -> root
    for s in range(S):
        for ci, ch in enumerate(chars):
            cmat[s, ci] = step(s, ch)
    match_arr = np.asarray(match, bool)

    n_states = S + 1  # + the terminal must-EOS state
    must_eos = S
    V = len(vocab)
    trans = np.zeros((n_states, V), np.int32)
    allowed = np.zeros((n_states, V), bool)
    all_states = np.arange(S)
    for t, text in enumerate(vocab):
        if t == eos_id or text == "":
            continue
        cur = all_states
        early = np.zeros((S,), bool)  # a stop completed STRICTLY inside the token
        for i, ch in enumerate(text):
            cur = cmat[cur, char_ix.get(ch, len(chars))]
            if i < len(text) - 1:
                early |= match_arr[cur]
        ok = ~early
        trans[:S][ok, t] = np.where(match_arr[cur[ok]], must_eos, cur[ok])
        allowed[:S][ok, t] = True
    allowed[:, eos_id] = True  # free generation may end at will; forced at must_eos
    trans[:, eos_id] = np.arange(n_states)  # terminal self-loops
    # match trie states are unreachable as targets (completing tokens map to
    # must_eos) but collapse their rows too; must-EOS allows ONLY eos
    for s in np.flatnonzero(match_arr):
        allowed[s, :] = False
        allowed[s, eos_id] = True
    allowed[must_eos, :] = False
    allowed[must_eos, eos_id] = True
    return TokenConstraint(trans=trans, allowed=allowed, eos_id=eos_id)


def vocab_from_tokenizer(tokenizer: Any) -> List[str]:
    """Best-effort ``token id -> decoded text`` list for a Hugging Face
    tokenizer, for :func:`compile_regex`. Decodes each id in isolation
    (``convert_ids_to_tokens`` + ``convert_tokens_to_string``) so BPE space
    markers (``Ġ``/``Ċ``) and sentencepiece ``▁`` become real characters;
    special tokens (bos/eos/pad/unk/additional) map to ``""`` so the compiler
    never allows them mid-output. Caveat: tokenizers whose detokenization is
    context-dependent beyond leading-space markers (rare) can drift — spot-check
    ``"".join(vocab[t] for t in tokenizer.encode(s, add_special_tokens=False))
    == s`` on your data before trusting a grammar with it."""
    size = int(tokenizer.vocab_size)
    extra = getattr(tokenizer, "added_tokens_encoder", {}) or {}
    size = max([size] + [i + 1 for i in extra.values()])
    special = set(getattr(tokenizer, "all_special_ids", []) or [])
    out: List[str] = []
    for i in range(size):
        if i in special:
            out.append("")
            continue
        try:
            token = tokenizer.convert_ids_to_tokens(i)
            if token is None:
                out.append("")
                continue
            text = tokenizer.convert_tokens_to_string([token])
            # sentencepiece detok strips a word-initial ▁'s space when the
            # token is FIRST in the sequence (transformers
            # LlamaTokenizer.convert_tokens_to_string) — but per-id extraction
            # makes every token first, which would drop every inter-word
            # space; re-prepend it (the same correction outlines/guidance make)
            if token.startswith("▁") and not text.startswith(" "):
                text = " " + text
        except Exception:
            out.append("")
            continue
        out.append(text)
    return out


class ConstraintSet:
    """A union of grammars in ONE table pair, renumbered so that a grammar is
    nothing but a start state: ``starts[g]`` for grammar id ``g``. Grammar id 0
    is always FREE (every token allowed, nothing enforced) so unconstrained and
    constrained rows batch together; user grammars get ids 1..n in the order
    given. One compiled decode program serves every member."""

    def __init__(self, constraints: Sequence[TokenConstraint]):
        if not constraints:
            raise ValueError("ConstraintSet needs at least one TokenConstraint")
        V = constraints[0].vocab_size
        eos = constraints[0].eos_id
        for c in constraints:
            if c.vocab_size != V or c.eos_id != eos:
                raise ValueError("all constraints must share one vocab and eos_id")
        # FREE grammar: one state, all tokens allowed, self-loop
        blocks_t = [np.zeros((1, V), np.int32)]
        blocks_a = [np.ones((1, V), bool)]
        starts = [0]
        offset = 1
        for c in constraints:
            blocks_t.append(c.trans + offset)
            blocks_a.append(c.allowed)
            starts.append(offset)
            offset += c.n_states
        self.trans = np.concatenate(blocks_t, axis=0)
        self.allowed = np.concatenate(blocks_a, axis=0)
        self.starts = np.asarray(starts, np.int32)
        self.eos_id = eos
        self._device_tables: Optional[Tuple[Any, Any]] = None

    def device_tables(self) -> Tuple[Any, Any]:
        """Memoized device copies ``(trans, allowed)`` shared by every engine
        built over this set — a real-tokenizer set is tens of MB ([S, 128k]
        int32 + bool), and a constrained speculative stack builds THREE
        Generators (plain, target, draft) that must not each ship their own."""
        if self._device_tables is None:
            import jax.numpy as jnp

            self._device_tables = (jnp.asarray(self.trans), jnp.asarray(self.allowed))
        return self._device_tables

    @property
    def n_grammars(self) -> int:
        """Including the implicit FREE grammar at id 0."""
        return len(self.starts)

    @property
    def vocab_size(self) -> int:
        return int(self.trans.shape[1])

    def start_states(self, grammar_ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(grammar_ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_grammars):
            raise ValueError(
                f"grammar id out of range [0, {self.n_grammars}) in {list(grammar_ids)}"
            )
        return self.starts[ids].astype(np.int32)
