"""Shared transformer building blocks (flax), written mesh-first.

No analog in the reference (it never looks inside a model, SURVEY.md §0); this is the
model library backing the BASELINE.json configs. Conventions:

- activations ``[batch, length, heads, head_dim]`` so sequence-parallel specs are
  rank-stable (:mod:`unionml_tpu.ops.ring_attention`);
- ``dtype`` (compute, default bf16 — the MXU native format) is separate from
  ``param_dtype`` (storage, default f32);
- parameter names are chosen so the PartitionRules regexes in
  :func:`unionml_tpu.models.llama.llama_partition_rules` etc. resolve TP layouts
  without per-model spec tables.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from unionml_tpu.ops.attention import multihead_attention

Dtype = Any

#: One layer's KV cache: ``{"k": [B, S_max, H_kv, D], "v": [B, S_max, H_kv, D]}``.
LayerCache = Dict[str, jax.Array]


def _write_cache(buffer: jax.Array, new: jax.Array, starts: jax.Array) -> jax.Array:
    """Write ``new: [B, L, H, D]`` into ``buffer: [B, S_max, H, D]`` at per-example
    row offsets ``starts: [B]`` (each example's sequence is contiguous in its own
    cache rows, so variable-length prompts need no left-padding)."""
    return jax.vmap(lambda buf, upd, s: lax.dynamic_update_slice(buf, upd, (s, 0, 0)))(
        buffer, new.astype(buffer.dtype), starts
    )


def quantize_kv_rows(x: jax.Array):
    """Symmetric per-(position, head) int8 for K/V rows: ``(int8 values, f32
    scales [..., 1])``. Shared by the int8-KV cached-attention write path and the
    sequence-parallel prefill's cache assembly."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(scale, 1e-8) / 127.0
    rows = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return rows.astype(jnp.int8), scale


class RMSNorm(nn.Module):
    """Root-mean-square layer norm (pre-norm default for decoder stacks)."""

    epsilon: float = 1e-6
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon)
        return (norm * scale).astype(self.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _embed_lookup(embedding: jax.Array, tokens: jax.Array, num_embeddings: int) -> jax.Array:
    return jnp.take(embedding, tokens, axis=0)


def _embed_lookup_fwd(embedding, tokens, num_embeddings):
    return jnp.take(embedding, tokens, axis=0), tokens


def _embed_lookup_bwd(num_embeddings, res, g):
    tokens = res  # g.dtype == the lookup's (and so the table operand's) dtype
    # dW as a one-hot matmul instead of take's scatter-add: with the table
    # vocab/dim-sharded the scatter cannot be partitioned and XLA falls back to
    # involuntary full rematerialization; the dot reduce-scatters cleanly, the
    # one-hot iota fuses into its tiles ([tokens, vocab] never materializes),
    # and a frozen table's dW (LoRA) is still dead-code-eliminated
    one_hot = jax.nn.one_hot(tokens, num_embeddings, dtype=g.dtype)
    axes = tuple(range(g.ndim - 1))
    dw = jax.lax.dot_general(
        one_hot, g, (((axes), (axes)), ((), ())), preferred_element_type=jnp.float32
    )
    return (dw.astype(g.dtype), None)


_embed_lookup.defvjp(_embed_lookup_fwd, _embed_lookup_bwd)


class IotaEmbed(nn.Module):
    """``nn.Embed`` with an SPMD-clean backward: gather forward, one-hot
    matmul backward (the train-side half of maxtext's ``use_iota_embed``).

    ``nn.Embed`` lowers to gather forward / scatter-add backward; with the
    table vocab/dim-sharded (Megatron vocab-parallel, the llama/moe partition
    rules) the SPMD partitioner cannot reshard the batch-sharded update into
    the table layout and falls back to "involuntary full rematerialization" —
    a per-step (per-microbatch, under grad accumulation) all-gather of the
    residual gradient. The backward here is a dot against a one-hot iota
    (same shapes as the lm_head matmul), which reduce-scatters cleanly.

    The FORWARD stays a gather on purpose: a full one-hot matmul would stream
    the whole table per call, which is irrelevant in training but ruinous in
    decode (a [B, 1] lookup reads rows, not gigabytes). Param path, shape,
    init, and looked-up values are identical to ``nn.Embed``, so partition
    rules and checkpoints are unaffected.
    """

    num_embeddings: int
    features: int
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        embedding = self.param(
            "embedding",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal", out_axis=0),
            (self.num_embeddings, self.features),
            self.param_dtype,
        )
        return _embed_lookup(embedding.astype(self.dtype), tokens, self.num_embeddings)


def rotary_embedding(x: jax.Array, positions: jax.Array, theta: float = 10000.0) -> jax.Array:
    """Apply RoPE to ``x: [B, L, H, D]`` at integer ``positions: [L]`` (or ``[B, L]``)."""
    head_dim = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., L, D/2]
    while angles.ndim < x.ndim:  # broadcast over batch/head dims
        angles = angles[None] if angles.ndim == 2 else angles[:, :, None]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


class LoRADense(nn.Module):
    """Dense layer with an optional low-rank adapter: ``y = xW + (xA)B * (alpha/r)``.

    With ``rank == 0`` this is a plain Dense. The adapter params live under
    ``lora_a``/``lora_b`` so :func:`unionml_tpu.models.llama.lora_param_labels` can
    mask the base weights out of the optimizer for LoRA fine-tuning.
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    use_bias: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (in_features, self.features), self.param_dtype)
        y = jnp.dot(x, kernel.astype(self.dtype))
        if self.rank > 0:
            a = self.param("lora_a", nn.initializers.normal(0.02), (in_features, self.rank), self.param_dtype)
            b = self.param("lora_b", nn.initializers.zeros, (self.rank, self.features), self.param_dtype)
            y = y + jnp.dot(jnp.dot(x, a.astype(self.dtype)), b.astype(self.dtype)) * (self.alpha / self.rank)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y


class Attention(nn.Module):
    """Multi-head (optionally grouped-query) attention with RoPE and impl dispatch.

    ``impl``: ``"auto"`` (currently XLA — flash stays opt-in until the pallas kernel
    beats XLA's fused attention on its benchmark; see
    :func:`unionml_tpu.ops.attention.multihead_attention`), ``"xla"``, ``"flash"``, or
    ``"ring"`` (sequence-parallel exact attention; requires running inside shard_map
    with a ``sequence`` axis), or ``"ulysses"`` (all-to-all sequence parallelism —
    same shard_map requirement, cheaper collectives when heads divide the axis).
    """

    n_heads: int
    n_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    causal: bool = False
    rope: bool = False
    rope_theta: float = 10000.0
    impl: str = "auto"
    lora_rank: int = 0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
        cache: Optional[LayerCache] = None,
    ) -> Any:
        features = x.shape[-1]
        n_kv = self.n_kv_heads or self.n_heads
        head_dim = self.head_dim or features // self.n_heads
        dense = lambda feats, name: LoRADense(  # noqa: E731
            feats, rank=self.lora_rank, dtype=self.dtype, param_dtype=self.param_dtype, name=name
        )

        q = dense(self.n_heads * head_dim, "q_proj")(x)
        k = dense(n_kv * head_dim, "k_proj")(x)
        v = dense(n_kv * head_dim, "v_proj")(x)

        batch, length = x.shape[0], x.shape[1]
        q = q.reshape(batch, length, self.n_heads, head_dim)
        k = k.reshape(batch, length, n_kv, head_dim)
        v = v.reshape(batch, length, n_kv, head_dim)

        if self.rope:
            if positions is None:
                positions = jnp.arange(length)
            q = rotary_embedding(q, positions, self.rope_theta)
            k = rotary_embedding(k, positions, self.rope_theta)

        if cache is not None:
            # Incremental decoding: the new rows' K/V land in the cache at each
            # example's next free slots (= the absolute positions), and attention
            # runs over the full static-shape buffer with an explicit visibility
            # mask — key slot j is visible to the query at absolute position p
            # iff j <= p, which is causal over everything written so far and
            # hides slots not yet (re)written. Static shapes throughout: the
            # decode step compiles exactly once per (batch, cache_len).
            if positions is None or positions.ndim != 2:
                raise ValueError("cached attention requires per-example positions [B, L]")
            if mask is not None:
                raise NotImplementedError("cached attention builds its own mask")
            if "table" in cache:
                # Paged KV (vLLM-style, static-shape): K/V live in a SHARED pool
                # of fixed-size blocks ([n_blocks, block_size, H_kv, D]) and each
                # batch row owns a block-table row mapping its logical positions
                # to pool blocks — HBM scales with the pool, not with
                # batch x worst-case length. Writes scatter through the table
                # (position p -> block table[b, p // bs], offset p % bs); reads
                # gather pool[table] back into the logical [B, MB * bs] layout,
                # so the visibility mask — and therefore the numerics — are
                # IDENTICAL to the contiguous branch below. Table rows of
                # finished/free slots are repointed to a scratch block by the
                # engine that owns the pool (see serving/continuous.py), which
                # is what makes their ride-along writes harmless.
                out, cache = self._paged_cached_attention(q, k, v, positions, cache)
                out = out.reshape(batch, length, self.n_heads * head_dim)
                return dense(features, "o_proj")(out), cache
            starts = positions[:, 0]
            if "k_scale" in cache:
                # int8 KV cache: symmetric per-(position, head) quantization on
                # write; dequant on read fuses into the attention contraction.
                # Long-context decode streams the cache every step — int8 halves
                # those bytes (scales are D/4x smaller than the values).
                kq, k_scale = quantize_kv_rows(k)
                vq, v_scale = quantize_kv_rows(v)
                cache = {
                    "k": _write_cache(cache["k"], kq, starts),
                    "v": _write_cache(cache["v"], vq, starts),
                    "k_scale": _write_cache(cache["k_scale"], k_scale, starts),
                    "v_scale": _write_cache(cache["v_scale"], v_scale, starts),
                }
                keys = (cache["k"].astype(jnp.float32) * cache["k_scale"]).astype(q.dtype)
                values = (cache["v"].astype(jnp.float32) * cache["v_scale"]).astype(q.dtype)
            else:
                cache = {
                    "k": _write_cache(cache["k"], k, starts),
                    "v": _write_cache(cache["v"], v, starts),
                }
                keys = cache["k"].astype(q.dtype)
                values = cache["v"].astype(q.dtype)
            slot = jnp.arange(cache["k"].shape[1])
            visible = slot[None, None, None, :] <= positions[:, None, :, None]  # [B,1,L,S_max]
            out = multihead_attention(q, keys, values, causal=False, mask=visible, impl="xla")
            out = out.reshape(batch, length, self.n_heads * head_dim)
            return dense(features, "o_proj")(out), cache

        # uncached forward: expose post-RoPE K/V for cache assembly (materialized
        # only when the caller passes mutable=["kvs"], e.g. the sequence-parallel
        # prefill; a plain apply pays nothing)
        self.sow("kvs", "k", k)
        self.sow("kvs", "v", v)

        if self.impl in ("ring", "ulysses"):
            if mask is not None:
                raise NotImplementedError("sequence-parallel attention does not support arbitrary masks")
            from unionml_tpu.ops.ring_attention import ring_attention, ulysses_attention

            sp_attention = ring_attention if self.impl == "ring" else ulysses_attention
            out = sp_attention(q, k, v, causal=self.causal)
        else:
            out = multihead_attention(q, k, v, causal=self.causal, mask=mask, impl=self.impl)

        out = out.reshape(batch, length, self.n_heads * head_dim)
        return dense(features, "o_proj")(out)

    def _paged_cached_attention(self, q, k, v, positions, cache):
        """The paged write+read: scatter new rows through the block table, then
        attend — via the pallas paged-attention kernel (``impl="flash"`` on TPU,
        single-token decode: pages stream block-by-block, no gathered copy) or
        the portable gather path (``pool[:, table]`` back to the logical layout
        under the same ``slot <= position`` visibility mask as the contiguous
        branch — numerically identical to it). Pools are heads-major
        ``[H_kv, n_pages, page_size, last]``. Scatter indices collide only on
        the scratch block (finished rows), where the winning value is
        irrelevant — real slots own disjoint blocks."""
        table = cache["table"]  # [B, max_blocks] int32
        block_size = cache["k"].shape[2]
        blk = jnp.take_along_axis(table, positions // block_size, axis=1)  # [B, L]
        off = positions % block_size

        def scatter(pool: jax.Array, rows: jax.Array) -> jax.Array:
            # rows [B, L, H_kv, last] -> pool[:, blk, off] has shape [H_kv, B, L, last]
            return pool.at[:, blk, off].set(jnp.moveaxis(rows, 2, 0).astype(pool.dtype))

        def logical(pool: jax.Array) -> jax.Array:
            rows = pool[:, table]  # [H_kv, B, MB, bs, last]
            rows = rows.reshape(rows.shape[0], rows.shape[1], -1, rows.shape[-1])
            return jnp.transpose(rows, (1, 2, 0, 3))  # [B, MB * bs, H_kv, last]

        use_kernel = self.impl == "flash" and q.shape[1] == 1
        if "k_scale" in cache:
            kq, k_scale = quantize_kv_rows(k)
            vq, v_scale = quantize_kv_rows(v)
            cache = {
                "k": scatter(cache["k"], kq),
                "v": scatter(cache["v"], vq),
                "k_scale": scatter(cache["k_scale"], k_scale),
                "v_scale": scatter(cache["v_scale"], v_scale),
                "table": table,
            }
            # int8 pages stay on the gather path even under impl="flash": the
            # library kernel broadcasts the per-position scales to FULL head
            # width and DMAs them alongside the int8 pages (5 B/elem vs bf16's
            # 2), so routing int8 through it would RAISE page traffic — the
            # shootout (bench_paged_attention.py) measures the kernel's int8
            # mode anyway, and this gate flips only if hardware disagrees
            keys = (logical(cache["k"]).astype(jnp.float32) * logical(cache["k_scale"])).astype(q.dtype)
            values = (logical(cache["v"]).astype(jnp.float32) * logical(cache["v_scale"])).astype(q.dtype)
        else:
            cache = {"k": scatter(cache["k"], k), "v": scatter(cache["v"], v), "table": table}
            if use_kernel:
                # single-token decode through the pallas kernel (TPU only); the
                # row's visible length includes the token just scattered
                from unionml_tpu.ops.paged_attention import paged_decode_attention

                out = paged_decode_attention(
                    q[:, 0], cache["k"], cache["v"], positions[:, 0] + 1, table
                )
                return out[:, None], cache
            keys = logical(cache["k"]).astype(q.dtype)
            values = logical(cache["v"]).astype(q.dtype)
        visible = (
            jnp.arange(keys.shape[1])[None, None, None, :] <= positions[:, None, :, None]
        )  # [B, 1, L, MB * bs]
        return multihead_attention(q, keys, values, causal=False, mask=visible, impl="xla"), cache


class MLP(nn.Module):
    """Feed-forward block: gated SwiGLU (decoder default) or plain GELU (encoder)."""

    hidden_dim: int
    gated: bool = True
    lora_rank: int = 0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        features = x.shape[-1]
        dense = lambda feats, name: LoRADense(  # noqa: E731
            feats, rank=self.lora_rank, dtype=self.dtype, param_dtype=self.param_dtype, name=name
        )
        if self.gated:
            gate = jax.nn.silu(dense(self.hidden_dim, "wg")(x))
            up = dense(self.hidden_dim, "wi")(x)
            return dense(features, "wo")(gate * up)
        h = jax.nn.gelu(dense(self.hidden_dim, "wi")(x))
        return dense(features, "wo")(h)


class TransformerBlock(nn.Module):
    """Pre-norm transformer block, encoder (bidirectional+LN) or decoder (causal+RMS)."""

    n_heads: int
    hidden_dim: int
    n_kv_heads: Optional[int] = None
    decoder: bool = True
    rope: bool = False
    rope_theta: float = 10000.0
    attention_impl: str = "auto"
    lora_rank: int = 0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        positions: Optional[jax.Array] = None,
        mask: Optional[jax.Array] = None,
        cache: Optional[LayerCache] = None,
    ) -> Any:
        norm = (
            (lambda name: RMSNorm(dtype=self.dtype, name=name))
            if self.decoder
            else (lambda name: nn.LayerNorm(dtype=self.dtype, name=name))
        )
        attn_out = Attention(
            n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads,
            causal=self.decoder,
            rope=self.rope,
            rope_theta=self.rope_theta,
            impl=self.attention_impl,
            lora_rank=self.lora_rank,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="attn",
        )(norm("attn_norm")(x), positions, mask, cache)
        if cache is not None:
            attn_out, cache = attn_out
        x = x + attn_out
        x = x + MLP(
            hidden_dim=self.hidden_dim,
            gated=self.decoder,
            lora_rank=self.lora_rank,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="mlp",
        )(norm("mlp_norm")(x))
        return (x, cache) if cache is not None else x
