"""BERT-family bidirectional encoder + classification head.

Backs BASELINE.json config 3 ("HF BERT-base SST-2 fine-tune, DP all-reduce over
v5e-8"). Standard learned-position encoder with pre-norm blocks; weights can be
imported from a HuggingFace checkpoint via :func:`load_hf_bert_params` (host-side
torch -> numpy conversion, no torch in the compiled path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from unionml_tpu.models.layers import TransformerBlock
from unionml_tpu.parallel.sharding import PartitionRules


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_classes: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def base(cls, **overrides: Any) -> "BertConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides: Any) -> "BertConfig":
        defaults = dict(vocab_size=512, dim=128, n_layers=2, n_heads=4, hidden_dim=256, max_seq_len=128)
        defaults.update(overrides)
        return cls(**defaults)


class BertEncoder(nn.Module):
    """Token/position/type embeddings -> encoder stack -> [CLS] pooled logits."""

    config: BertConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        token_type_ids: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        length = tokens.shape[1]
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="tok_embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="pos_embed")(
            jnp.arange(length)
        )
        x = x + pos[None]
        if token_type_ids is not None:
            x = x + nn.Embed(
                cfg.type_vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="type_embed"
            )(token_type_ids)
        x = nn.LayerNorm(dtype=cfg.dtype, name="embed_norm")(x)

        # [B, L] padding mask (1 = real token) -> [B, 1, 1, L] broadcast over heads
        # and query positions, so pad tokens are never attended to
        mask = attention_mask[:, None, None, :].astype(bool) if attention_mask is not None else None

        for i in range(cfg.n_layers):
            x = TransformerBlock(
                n_heads=cfg.n_heads,
                hidden_dim=cfg.hidden_dim,
                decoder=False,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name=f"layer_{i}",
            )(x, mask=mask)

        pooled = jnp.tanh(
            nn.Dense(cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="pooler")(x[:, 0])
        )
        return nn.Dense(cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="classifier")(pooled)


def bert_partition_rules() -> PartitionRules:
    return PartitionRules(
        [
            (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
            (r"attn/o_proj/kernel", P("model", "fsdp")),
            (r"mlp/wi/kernel", P("fsdp", "model")),
            (r"mlp/wo/kernel", P("model", "fsdp")),
            (r"(tok|pos|type)_embed/embedding", P(None, "fsdp")),
            (r"(pooler|classifier)/kernel", P("fsdp", None)),
            (r".*(norm|scale|bias)", P()),
        ]
    )


def classification_loss(apply_fn, params, batch) -> Any:
    """(tokens, labels) or (tokens, attention_mask, labels) -> (loss, {'accuracy': ...});
    use with make_train_step(has_aux=True)."""
    import optax

    if len(batch) == 3:
        tokens, attention_mask, labels = batch
        logits = apply_fn(params, tokens, attention_mask)
    else:
        tokens, labels = batch
        logits = apply_fn(params, tokens)
    labels = labels.reshape(-1).astype(jnp.int32)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits.astype(jnp.float32), labels).mean()
    accuracy = (jnp.argmax(logits, -1) == labels).mean()
    return loss, {"accuracy": accuracy}


def load_hf_bert_params(hf_model_name: str, config: BertConfig):  # pragma: no cover - network/weights
    """Convert a HuggingFace torch BERT checkpoint into this module's param tree.

    Host-side only (numpy); the compiled path never touches torch. Requires the
    checkpoint to be available locally (zero-egress environments must pre-seed the
    HF cache).
    """
    import numpy as np
    from transformers import AutoModel

    hf = AutoModel.from_pretrained(hf_model_name)
    sd = {k: np.asarray(v.detach()) for k, v in hf.state_dict().items()}

    def dense(prefix):
        return {"kernel": sd[f"{prefix}.weight"].T, "bias": sd[f"{prefix}.bias"]}

    params = {
        "tok_embed": {"embedding": sd["embeddings.word_embeddings.weight"]},
        "pos_embed": {"embedding": sd["embeddings.position_embeddings.weight"][: config.max_seq_len]},
        "type_embed": {"embedding": sd["embeddings.token_type_embeddings.weight"]},
        "embed_norm": {"scale": sd["embeddings.LayerNorm.weight"], "bias": sd["embeddings.LayerNorm.bias"]},
        "pooler": dense("pooler.dense"),
    }
    for i in range(config.n_layers):
        hf_prefix = f"encoder.layer.{i}"
        params[f"layer_{i}"] = {
            "attn_norm": {
                "scale": sd[f"{hf_prefix}.attention.output.LayerNorm.weight"],
                "bias": sd[f"{hf_prefix}.attention.output.LayerNorm.bias"],
            },
            "attn": {
                "q_proj": {"kernel": sd[f"{hf_prefix}.attention.self.query.weight"].T},
                "k_proj": {"kernel": sd[f"{hf_prefix}.attention.self.key.weight"].T},
                "v_proj": {"kernel": sd[f"{hf_prefix}.attention.self.value.weight"].T},
                "o_proj": {"kernel": sd[f"{hf_prefix}.attention.output.dense.weight"].T},
            },
            "mlp_norm": {
                "scale": sd[f"{hf_prefix}.output.LayerNorm.weight"],
                "bias": sd[f"{hf_prefix}.output.LayerNorm.bias"],
            },
            "mlp": {
                "wi": {"kernel": sd[f"{hf_prefix}.intermediate.dense.weight"].T},
                "wo": {"kernel": sd[f"{hf_prefix}.output.dense.weight"].T},
            },
        }
    return params
