"""Llama-family causal decoder with LoRA and TP/FSDP/SP partition rules.

Backs BASELINE.json config 4 ("Llama-3-8B LoRA fine-tune + serve, pjit FSDP"). The
module is a standard pre-norm RoPE/SwiGLU/GQA decoder; parallelism comes entirely
from the outside: the train driver resolves :func:`llama_partition_rules` (megatron
TP + fsdp) against the param tree, the sequence axis rides
:mod:`unionml_tpu.ops.ring_attention` when ``attention_impl='ring'``, and
:func:`lora_param_labels` masks the base weights out of the optimizer for LoRA.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from unionml_tpu.models.layers import IotaEmbed, RMSNorm, TransformerBlock
from unionml_tpu.parallel.sharding import PartitionRules


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    lora_rank: int = 0
    attention_impl: str = "auto"
    remat: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def llama3_8b(cls, **overrides: Any) -> "LlamaConfig":
        defaults = dict(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            hidden_dim=14336, rope_theta=500000.0,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def tiny(cls, **overrides: Any) -> "LlamaConfig":
        """Test/dry-run scale."""
        defaults = dict(
            vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=256, max_seq_len=256,
        )
        defaults.update(overrides)
        return cls(**defaults)


class Llama(nn.Module):
    """Causal LM: tokens ``[B, L]`` -> logits ``[B, L, vocab]``."""

    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        return_hidden: bool = False,
        cache: Optional[Tuple[Any, ...]] = None,
        token_mask: Optional[jax.Array] = None,
    ) -> Any:
        """``cache`` (one :data:`~unionml_tpu.models.layers.LayerCache` per layer,
        see :func:`unionml_tpu.models.generate.init_cache`) switches the stack into
        incremental-decoding mode: the return value becomes ``(out, new_cache)``
        and ``positions`` must be per-example absolute positions ``[B, L]``.

        ``token_mask`` (``[B, L]`` bool, False = padding) is part of the shared
        cache contract so the Generator can drive dense and routed decoders
        uniformly; a dense decoder ignores it — rows are independent and causal
        masking already hides right-padding from real tokens."""
        del token_mask
        cfg = self.config
        # one-hot-matmul lookup: same params as nn.Embed, SPMD-clean backward
        # (nn.Embed's scatter-add cannot partition into the vocab-sharded table)
        x = IotaEmbed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="embed"
        )(tokens)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])

        block_cls = TransformerBlock
        if cfg.remat:
            block_cls = nn.remat(TransformerBlock, static_argnums=())
        new_cache = []
        for i in range(cfg.n_layers):
            block = block_cls(
                n_heads=cfg.n_heads,
                n_kv_heads=cfg.n_kv_heads,
                hidden_dim=cfg.hidden_dim,
                decoder=True,
                rope=True,
                rope_theta=cfg.rope_theta,
                attention_impl=cfg.attention_impl,
                lora_rank=cfg.lora_rank,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name=f"layer_{i}",
            )
            if cache is not None:
                x, layer_cache = block(x, positions, None, cache[i])
                new_cache.append(layer_cache)
            else:
                x = block(x, positions)

        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        if return_hidden:
            # pre-head hidden states for chunked-loss paths; init always runs with
            # return_hidden=False so the lm_head params exist in the tree (flax
            # ignores unvisited params at apply time)
            return (x, tuple(new_cache)) if cache is not None else x
        # untied LM head (kept separate so vocab-parallel TP sharding is per-rule)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="lm_head"
        )(x)
        return (logits, tuple(new_cache)) if cache is not None else logits


def llama_partition_rules() -> PartitionRules:
    """Megatron-style TP layout + vocab-parallel embedding/head.

    Column-parallel (shard output dim over ``model``): q/k/v, mlp wi/wg.
    Row-parallel (shard input dim over ``model``): o_proj, mlp wo.
    The complementary dim takes ``fsdp`` so ZeRO-3 and TP compose on a 2D mesh.
    """
    return PartitionRules(
        [
            (r"attn/(q_proj|k_proj|v_proj)/kernel", P("fsdp", "model")),
            (r"attn/o_proj/kernel", P("model", "fsdp")),
            (r"mlp/(wi|wg)/kernel", P("fsdp", "model")),
            (r"mlp/wo/kernel", P("model", "fsdp")),
            (r"embed/embedding", P("model", "fsdp")),
            (r"lm_head/kernel", P("fsdp", "model")),
            (r"lora_a", P("fsdp", None)),
            (r"lora_b", P(None, "model")),
            (r".*(norm|scale|bias)", P()),
        ]
    )


def lora_param_labels(params: Dict[str, Any]) -> Dict[str, Any]:
    """Label pytree for ``optax.multi_transform``: ``"lora"`` for adapter params,
    ``"frozen"`` for base weights — LoRA fine-tuning trains ~0.5% of the params."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: "lora" if any("lora" in str(getattr(p, "key", "")) for p in path) else "frozen",
        params,
    )


def lora_optimizer(learning_rate: float = 1e-4, **adam_kwargs: Any):
    """Adam on LoRA params only; base weights frozen via ``optax.set_to_zero``."""
    import optax

    return optax.multi_transform(
        {"lora": optax.adamw(learning_rate, **adam_kwargs), "frozen": optax.set_to_zero()},
        lora_param_labels,
    )


def chunked_causal_lm_loss(module: "Llama", params, batch, *, chunk_size: int = 256) -> jax.Array:
    """Next-token cross-entropy without materializing the full ``[B, S, vocab]``
    f32 logits tensor.

    For large vocabularies (Llama-3: 128k) the f32 logits of a whole sequence are
    the peak-memory *and* bandwidth hot spot of the training step — at B=4, S=1024
    they are 2 GiB that the plain loss writes to and re-reads from HBM. This variant
    runs the LM head + softmax over ``chunk_size``-token slices under ``lax.scan``
    with a rematerialized body, so peak logits memory drops to
    ``B * chunk_size * vocab`` and the backward pass recomputes each chunk's logits
    instead of storing them. Numerically identical to :func:`causal_lm_loss`.
    """
    import optax

    tokens, mask = (batch if isinstance(batch, (tuple, list)) and len(batch) == 2 else (batch, None))
    if isinstance(tokens, (tuple, list)):
        tokens = tokens[0]
    hidden = module.apply({"params": params}, tokens, return_hidden=True)  # [B, S, D]
    head = params["lm_head"]["kernel"]  # [D, V]
    hidden, targets = hidden[:, :-1], tokens[:, 1:]
    valid = jnp.ones(targets.shape, jnp.float32) if mask is None else mask[:, 1:].astype(jnp.float32)

    batch_dim, seq, dim = hidden.shape
    pad = (-seq) % chunk_size
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    n_chunks = (seq + pad) // chunk_size
    # scan over chunks: [n, B, chunk, ...]
    hs = hidden.reshape(batch_dim, n_chunks, chunk_size, dim).swapaxes(0, 1)
    ts = targets.reshape(batch_dim, n_chunks, chunk_size).swapaxes(0, 1)
    ms = valid.reshape(batch_dim, n_chunks, chunk_size).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_loss(h, t, m):
        logits = (h @ head.astype(h.dtype)).astype(jnp.float32)
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, t)
        return (losses * m).sum()

    def body(total, xs):
        h, t, m = xs
        return total + chunk_loss(h, t, m), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (hs, ts, ms))
    return total / jnp.maximum(valid.sum(), 1.0)


def causal_lm_loss(apply_fn, params, batch) -> jax.Array:
    """Next-token cross-entropy. ``batch``: ``(tokens, loss_mask?)`` or tokens array."""
    tokens, mask = (batch if isinstance(batch, (tuple, list)) and len(batch) == 2 else (batch, None))
    if isinstance(tokens, (tuple, list)):
        tokens = tokens[0]
    logits = apply_fn(params, tokens)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    import optax

    losses = optax.softmax_cross_entropy_with_integer_labels(logits.astype(jnp.float32), targets)
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        return (losses * m).sum() / jnp.maximum(m.sum(), 1.0)
    return losses.mean()
