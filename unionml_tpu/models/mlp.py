"""Flax MLP classifier — the minimal step-mode model (BASELINE.json config 2)."""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    features: Sequence[int] = (512, 256)
    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


class MLPClassifier(nn.Module):
    config: MLPConfig = MLPConfig()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        x = x.reshape(x.shape[0], -1).astype(cfg.dtype)
        for i, width in enumerate(cfg.features):
            x = nn.Dense(width, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        return nn.Dense(cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="head")(x)


def make_train_state(config: MLPConfig, input_dim: int, learning_rate: float = 1e-3, seed: int = 0):
    """Convenience ``init`` for ``Model(init=...)`` apps."""
    import optax
    from flax.training import train_state

    module = MLPClassifier(config)
    params = module.init(jax.random.PRNGKey(seed), jnp.zeros((1, input_dim)))["params"]
    return train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(learning_rate))
