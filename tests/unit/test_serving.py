"""Serving app tests: in-process dispatch + micro-batcher. The live-socket HTTP
framing tests (chunked streaming, HTTP/1.0 fallback, keep-alive) and the
CLI-booted subprocess server live in tests/integration/."""

import asyncio
import json

import pytest

from unionml_tpu.serving import MicroBatcher, ServingConfig, serving_app


def _dispatch(app, method, path, body=b""):
    return asyncio.run(app.dispatch(method, path, body))


@pytest.fixture
def trained_app(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    return serving_app(sklearn_model)


def test_root_banner(trained_app):
    status, payload, content_type = _dispatch(trained_app, "GET", "/")
    assert status == 200
    assert content_type == "text/html"
    assert "unionml-tpu" in payload


def test_health(trained_app):
    status, payload, _ = _dispatch(trained_app, "GET", "/health")
    assert status == 200
    assert payload["status"] == 200


def test_health_without_artifact(sklearn_model):
    app = serving_app(sklearn_model)
    app._started = True  # skip startup loading
    status, payload, _ = _dispatch(app, "GET", "/health")
    assert status == 500
    assert "not found" in payload["detail"].lower()


def test_predict_with_features(trained_app):
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}, {"x1": -1.0, "x2": -1.0}]}).encode()
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", body)
    assert status == 200
    assert payload == [1.0, 0.0]


def test_predict_with_inputs(trained_app):
    body = json.dumps({"inputs": {"sample_frac": 1.0, "random_state": 0}}).encode()
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", body)
    assert status == 200
    assert len(payload) == 100


def test_predict_requires_inputs_or_features(trained_app):
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", b"{}")
    assert status == 500
    assert "inputs or features" in payload["detail"]


def test_predict_invalid_json(trained_app):
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", b"{not json")
    assert status == 400


def test_unknown_route_and_method(trained_app):
    status, *_ = _dispatch(trained_app, "GET", "/nope")
    assert status == 404
    status, *_ = _dispatch(trained_app, "DELETE", "/predict")
    assert status == 405


def test_startup_requires_model_path(sklearn_model, monkeypatch):
    monkeypatch.delenv("UNIONML_MODEL_PATH", raising=False)
    app = serving_app(sklearn_model)
    with pytest.raises(ValueError, match="artifact path not specified"):
        asyncio.run(app.dispatch("GET", "/health"))


def test_startup_loads_from_env(sklearn_model, tmp_path, monkeypatch):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    path = tmp_path / "m.joblib"
    sklearn_model.save(str(path))
    sklearn_model.artifact = None
    monkeypatch.setenv("UNIONML_MODEL_PATH", str(path))
    app = serving_app(sklearn_model)
    status, *_ = _dispatch(app, "GET", "/health")
    assert status == 200


def test_micro_batcher_coalesces_requests():
    calls = []

    def predict(batch):
        calls.append(len(batch))
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50))
        results = await asyncio.gather(*(batcher.submit([i]) for i in range(6)))
        await batcher.stop()
        return results

    results = asyncio.run(scenario())
    assert sorted(r[0] for r in results) == [0, 2, 4, 6, 8, 10]
    assert len(calls) < 6  # at least some requests shared a dispatch
    buckets = ServingConfig(max_batch_size=8).buckets()
    assert all(n in buckets for n in calls)  # dispatches are padded to bucket shapes


def test_micro_batcher_propagates_errors():
    def predict(batch):
        raise RuntimeError("boom")

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=4, max_wait_ms=5))
        with pytest.raises(RuntimeError, match="boom"):
            await batcher.submit([1])
        await batcher.stop()

    asyncio.run(scenario())


def test_metrics_endpoint_reports_latency_percentiles(trained_app):
    for _ in range(5):
        body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()
        status, _, _ = _dispatch(trained_app, "POST", "/predict", body)
        assert status == 200
    _dispatch(trained_app, "POST", "/predict", b"not json")  # counted as an error

    status, snapshot, _ = _dispatch(trained_app, "GET", "/metrics")
    assert status == 200
    assert snapshot["requests_total"] >= 6
    assert snapshot["errors_total"] >= 1
    predict = snapshot["routes"]["POST /predict"]
    assert predict["requests"] >= 6 and predict["errors"] >= 1
    assert predict["p50_ms"] > 0 and predict["p99_ms"] >= predict["p50_ms"]


def test_predict_stream_requires_registration(trained_app):
    status, payload, _ = _dispatch(
        trained_app, "POST", "/predict-stream", json.dumps({"features": []}).encode()
    )
    assert status == 404
    assert "stream predictor" in payload["detail"]


def test_predict_stream_setup_error_is_500_not_truncated_200(sklearn_model):
    """Generator-function predictors defer their body to the first next(); the
    route must surface that first failure as a clean 500, not a truncated 200."""
    sklearn_model.train(hyperparameters={"max_iter": 500})

    @sklearn_model.stream_predictor
    def stream_predictor(model_object, features):
        raise RuntimeError("boom")
        yield  # pragma: no cover

    app = serving_app(sklearn_model)
    status, payload, _ = _dispatch(
        app, "POST", "/predict-stream", json.dumps({"features": [{"x": 1.0}]}).encode()
    )
    assert status == 500 and "boom" in payload["detail"]

    # body contract matches /predict: a non-dict JSON body is a 400
    status, payload, _ = _dispatch(app, "POST", "/predict-stream", b"[1, 2]")
    assert status == 400 and "JSON object" in payload["detail"]
